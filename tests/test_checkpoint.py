"""Checkpoint/resume tests: sharded train state and per-expert server state."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from learning_at_home_tpu.models.transformer import (
    DMoETransformerConfig,
    DMoETransformerLM,
)
from learning_at_home_tpu.parallel import batch_sharding, make_mesh
from learning_at_home_tpu.utils.checkpoint import (
    CheckpointManager,
    TrainCheckpointer,
    latest_step,
    list_steps,
    mark_step_complete,
    next_step,
    prune_old_steps,
    save_pytree,
)


def test_train_checkpointer_roundtrip_sharded(tmp_path):
    mesh = make_mesh({"data": 2, "expert": 4})
    cfg = DMoETransformerConfig(
        vocab_size=64, d_model=32, n_layers=1, n_heads=4, seq_len=16,
        num_experts=8, k=2, dtype=jnp.float32,
    )
    model = DMoETransformerLM(cfg, mesh)
    params = model.init_params(jax.random.PRNGKey(0))
    opt = optax.adamw(1e-3)
    opt_state = model.init_opt_state(opt, params)
    step_fn = model.make_train_step(opt)

    rs = np.random.RandomState(0)
    ids = jax.device_put(jnp.asarray(rs.randint(0, 64, (8, 16))), batch_sharding(mesh))
    tgt = jax.device_put(jnp.asarray(rs.randint(0, 64, (8, 16))), batch_sharding(mesh))
    params, opt_state, loss1, _ = step_fn(params, opt_state, ids, tgt)

    ckpt = TrainCheckpointer(str(tmp_path / "ckpt"), keep_last=2)
    ckpt.save(1, params, opt_state)
    assert latest_step(str(tmp_path / "ckpt")) == 1

    # fresh model instance restores onto the SAME shardings
    model2 = DMoETransformerLM(cfg, mesh)
    params2 = model2.init_params(jax.random.PRNGKey(99))  # different values
    opt_state2 = model2.init_opt_state(opt, params2)
    restored = ckpt.restore_latest(params2, opt_state2)
    assert restored is not None
    step, rparams, ropt = restored
    assert step == 1
    # exact value match
    for a, b in zip(
        jax.tree_util.tree_leaves(params), jax.tree_util.tree_leaves(rparams)
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # sharding preserved on expert stacks
    assert rparams["layers"]["moe"]["w1"].sharding.spec == params[
        "layers"
    ]["moe"]["w1"].sharding.spec
    # resumed training continues identically
    _, _, loss_resumed, _ = step_fn(rparams, ropt, ids, tgt)
    _, _, loss_orig, _ = step_fn(params, opt_state, ids, tgt)
    np.testing.assert_allclose(float(loss_resumed), float(loss_orig), rtol=1e-5)


def test_train_checkpointer_prunes(tmp_path):
    ckpt = TrainCheckpointer(str(tmp_path / "c"), keep_last=2)
    tree = {"a": jnp.ones(3)}
    for s in (1, 2, 3, 4):
        ckpt.save(s, tree, tree)
    assert list_steps(str(tmp_path / "c")) == [3, 4]


# ---------------------------------------------------------------------------
# crash safety (ISSUE 9 satellites): a kill mid-save must never corrupt
# recovery, and pruning must never delete the only complete step
# ---------------------------------------------------------------------------


def _crash_mid_save(root: str, step: int, tree):
    """Simulate a kill between item writes and the completion marker:
    the step directory exists with saved items but NO marker."""
    save_pytree(root, step, "params", tree)
    # crash here: mark_step_complete(root, step) never runs


def test_restore_latest_ignores_kill_mid_save(tmp_path):
    """Kill mid-save → restore_latest returns the last COMPLETE step."""
    root = str(tmp_path / "crash")
    tree_v1 = {"a": jnp.arange(4.0)}
    ckpt = TrainCheckpointer(root, keep_last=3)
    ckpt.save(1, tree_v1, tree_v1)
    # a newer save dies after writing items but before the marker
    _crash_mid_save(root, 2, {"a": jnp.zeros(4)})
    assert latest_step(root) == 1
    assert list_steps(root, only_complete=False) == [1, 2]
    restored = ckpt.restore_latest(tree_v1, tree_v1)
    assert restored is not None
    step, params, _ = restored
    assert step == 1
    np.testing.assert_array_equal(np.asarray(params["a"]), np.arange(4.0))
    # the next save NEVER reuses the crashed step's directory (a retry
    # merging into half-written items would be unverifiable)
    assert next_step(root) == 3


def test_prune_never_deletes_only_complete_step(tmp_path):
    root = str(tmp_path / "prune")
    tree = {"a": jnp.ones(2)}
    save_pytree(root, 5, "item", tree)
    mark_step_complete(root, 5)
    # crashed half-saves around it, newer and older
    _crash_mid_save(root, 3, tree)
    _crash_mid_save(root, 7, tree)
    prune_old_steps(root, keep_last=1)
    # the only complete step survives any keep_last >= 1 ...
    assert list_steps(root) == [5]
    # ... the OLD crashed step is swept, and the NEWEST directory is
    # kept (it may be another process's save still in progress)
    assert list_steps(root, only_complete=False) == [5, 7]
    prune_old_steps(root, keep_last=5)
    assert list_steps(root) == [5]


def test_checkpoint_manager_periodic_prune_and_restart_counter(tmp_path):
    import time

    root = str(tmp_path / "mgr")
    tree = {"a": jnp.ones(2)}

    def save_fn(step):
        save_pytree(root, step, "item", tree)
        mark_step_complete(root, step)

    mgr = CheckpointManager(root, keep_last=2)
    assert mgr.save_now(save_fn) == 1
    assert mgr.save_now(save_fn) == 2
    assert mgr.save_now(save_fn) == 3
    assert list_steps(root) == [2, 3]  # pruned to keep_last
    assert mgr.saves == 3

    # a failing save_fn is counted, never raises out of the manager
    def bad_save(step):
        raise RuntimeError("disk full")

    assert mgr.save_now(bad_save) is None
    assert mgr.save_failures == 1

    # periodic thread keeps stepping until stopped
    mgr2 = CheckpointManager(root, keep_last=2)
    mgr2.start_periodic(save_fn, every_s=0.05)
    deadline = time.time() + 10
    while time.time() < deadline and mgr2.saves < 2:
        time.sleep(0.05)
    mgr2.stop()
    assert mgr2.saves >= 2
    saved = mgr2.saves
    time.sleep(0.2)
    assert mgr2.saves == saved  # really stopped

    # restart counter persists across manager instances (it counts the
    # restarts it survives)
    assert mgr.restart_count() == 0
    assert mgr.record_restart() == 1
    assert CheckpointManager(root).restart_count() == 1
    assert CheckpointManager(root).record_restart() == 2


def test_server_checkpoint_resume(tmp_path):
    from learning_at_home_tpu.server.server import background_server

    root = str(tmp_path / "server_ckpt")
    with background_server(num_experts=2, hidden_dim=16, seed=1) as (ep, srv):
        # do one update so state differs from init
        x = np.random.RandomState(0).randn(4, 16).astype(np.float32)
        g = np.ones((4, 16), np.float32)
        srv.experts["expert.0"].backward([x], [g])
        srv.save_checkpoint(root, step=7)
        want = {
            uid: b.state_dict()["params"] for uid, b in srv.experts.items()
        }

    # a NEW server (fresh params) restores the snapshot
    with background_server(num_experts=2, hidden_dim=16, seed=999) as (ep, srv2):
        restored_step = srv2.load_checkpoint(root)
        assert restored_step == 7
        for uid, backend in srv2.experts.items():
            got = backend.state_dict()["params"]
            for a, b in zip(
                jax.tree_util.tree_leaves(want[uid]),
                jax.tree_util.tree_leaves(got),
            ):
                np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        assert srv2.experts["expert.0"].update_count == 1
