"""Deterministic-dropout expert over the wire (reference layer-zoo parity).

The server re-forwards inside backward (one jitted vjp), so dropout must
be a pure function of wire inputs: the mask derives from a per-row int32
seed tensor.  These tests pin (a) determinism given the seed, (b) exact
gradients through the RPC boundary including the float0 cotangent path
for the integer seed input, (c) the async server-side update firing.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from learning_at_home_tpu.client import RemoteExpert, reset_client_rpc
from learning_at_home_tpu.models.layers import DeterministicDropoutBlock
from learning_at_home_tpu.server import Server

HID = 16


@pytest.fixture(scope="module")
def dropout_server():
    server = Server.create(
        num_experts=1,
        expert_cls="det_dropout",
        hidden_dim=HID,
        warmup=[4],
        host="127.0.0.1",
    )
    yield server
    server.shutdown()
    reset_client_rpc()


def test_forward_deterministic_in_seed(dropout_server):
    expert = RemoteExpert("expert.0", dropout_server.endpoint)
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(4, HID).astype(np.float32))
    s1 = jnp.arange(4, dtype=jnp.int32)
    out_a = np.asarray(expert(x, s1))
    out_b = np.asarray(expert(x, s1))
    np.testing.assert_array_equal(out_a, out_b)  # same seed → same mask
    out_c = np.asarray(expert(x, s1 + 100))
    assert not np.allclose(out_a, out_c)  # different seed → different mask


def test_matches_local_and_grads_flow(dropout_server):
    server = dropout_server
    expert = RemoteExpert("expert.0", server.endpoint)
    params = server.experts["expert.0"].state_dict()["params"]
    module = DeterministicDropoutBlock(hidden_dim=HID)

    rs = np.random.RandomState(1)
    x = jnp.asarray(rs.randn(4, HID).astype(np.float32))
    seed = jnp.asarray([7, 8, 9, 10], dtype=jnp.int32)

    np.testing.assert_allclose(
        np.asarray(expert(x, seed)),
        np.asarray(module.apply(params, x, seed)),
        atol=1e-5,
    )

    # grad w.r.t. x crosses the RPC boundary; the int seed primal takes
    # the float0 path client-side and the zeros-sanitizer server-side
    def remote_loss(x):
        return jnp.sum(expert(x, seed) ** 2)

    def local_loss(x):
        return jnp.sum(module.apply(params, x, seed) ** 2)

    before = server.experts["expert.0"].update_count
    g = jax.jit(jax.grad(remote_loss))(x)
    g_exp = jax.grad(local_loss)(x)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_exp), atol=1e-4)
    assert server.experts["expert.0"].update_count == before + 1


def test_backward_reforward_uses_same_mask(dropout_server):
    """The gradient magnitude itself proves mask reuse: with rate≈0.1 a
    re-drawn mask would zero/unzero different hidden units between the
    forward and the vjp re-forward, and the numeric check above would
    diverge.  Here we additionally pin that dropped units contribute
    exactly zero gradient."""
    server = dropout_server
    params = server.experts["expert.0"].state_dict()["params"]
    module = DeterministicDropoutBlock(hidden_dim=HID)
    x = jnp.ones((2, HID), jnp.float32)
    # seeds PINNED to a pair whose masks share a dropped unit: with
    # rate 0.1 over 64 units, P(a given unit dropped in both rows) is
    # 0.01 — the old (3, 4) pair happened to share none, so the
    # both-dropped assertion below failed on pure seed luck, not on any
    # contract violation.  (6, 10) shares 3 dropped units (verified),
    # exercising the documented zero-gradient contract robustly.
    seed = jnp.asarray([6, 10], dtype=jnp.int32)

    mask = jax.vmap(
        lambda s: jax.random.bernoulli(jax.random.PRNGKey(s), 0.9, (4 * HID,))
    )(seed)
    # gradient of the first Dense's output w.r.t. its own pre-mask value
    # is zero exactly where the mask dropped
    def hidden_sum(params):
        return jnp.sum(module.apply(params, x, seed))

    g = jax.grad(hidden_sum)(params)
    w2_grad_rows = np.asarray(
        g["params"]["Dense_1"]["kernel"]
    )  # [4H, H]: rows for dropped units must be zero for BOTH rows' masks
    both_dropped = ~np.asarray(mask[0]) & ~np.asarray(mask[1])
    assert both_dropped.any(), "test seeds should drop at least one unit"
    np.testing.assert_array_equal(
        w2_grad_rows[both_dropped], np.zeros_like(w2_grad_rows[both_dropped])
    )
