"""Seeded-violation tests for the runtime concurrency sanitizer (ISSUE 6).

Each detector must TRIP on a deliberately constructed violation — a
sanitizer that never fires is indistinguishable from one that works.
Expected findings are drained through ``sanitizer.expect_violations()``
so the shared conftest zero-violation guard stays green."""

import asyncio
import threading
import time

import numpy as np
import pytest

from learning_at_home_tpu.utils import sanitizer
from learning_at_home_tpu.utils.asyncio_utils import BackgroundLoop

pytestmark = pytest.mark.skipif(
    not sanitizer.enabled(), reason="sanitizer disabled (LAH_SANITIZE=0)"
)


# ---------------------------------------------------------------------------
# thread-identity detectors
# ---------------------------------------------------------------------------


def test_seeded_blocking_encode_on_client_loop_trips():
    """A deliberate 8-bit encode ON the lah-client loop — the exact
    blocking-work-on-the-loop regression PR 2/5 guard against — must be
    recorded as a thread violation naming the site and the loop."""
    from learning_at_home_tpu.client.rpc import client_loop, reset_client_rpc
    from learning_at_home_tpu.utils.serialization import EncodedBatch

    x = np.random.RandomState(0).randn(8, 16).astype(np.float32)

    async def encode_on_loop():
        return EncodedBatch.encode(x, "blockq8")

    with sanitizer.expect_violations("EncodedBatch.encode") as seen:
        eb = client_loop().run(encode_on_loop())
    reset_client_rpc()
    assert eb.codec == "blockq8"  # the check diagnoses, never intervenes
    hits = [
        v for v in seen
        if v["kind"] == "thread" and v["site"] == "EncodedBatch.encode"
    ]
    assert hits, f"seeded on-loop encode not detected: {seen}"
    assert hits[0]["thread"].startswith("lah-client")


def test_seeded_wrong_thread_stack_trips():
    """``BatchJob.stack`` called on an event loop (instead of the
    Runtime thread) must trip the ``runs_on("runtime")`` assertion."""
    from learning_at_home_tpu.server.staging import StagingBuffers
    from learning_at_home_tpu.server.task_pool import BatchJob, TaskPool

    pool = TaskPool(lambda i: list(i), "seeded", max_batch_size=8)
    tensors = [
        (np.ones((2, 4), np.float32),),
        (np.zeros((3, 4), np.float32),),
    ]
    job = BatchJob(
        priority=0.0, seq=0, pool=pool, task_tensors=tensors,
        row_spans=[], n_rows=5, target_rows=8,
        dtypes=[np.dtype(np.float32)],
    )

    async def stack_on_loop():
        return job.stack(StagingBuffers())

    with sanitizer.expect_violations("BatchJob.stack") as seen:
        inputs, buffers = asyncio.run(stack_on_loop())
    np.testing.assert_array_equal(inputs[0][:2], 1.0)  # still correct
    hits = [
        v for v in seen
        if v["kind"] == "thread" and v["site"] == "BatchJob.stack"
    ]
    assert hits, f"seeded on-loop stack not detected: {seen}"


def test_seeded_pack_frames_on_runtime_thread_trips():
    """The device thread must never serialize wire frames
    (``runs_on("not:lah-runtime")``)."""
    from learning_at_home_tpu.utils.serialization import (
        WireTensors,
        pack_frames,
    )

    wire = WireTensors.prepare([np.zeros(4, np.float32)])
    out = {}

    def on_fake_runtime():
        out["parts"] = pack_frames("forward", wire, {"uid": "x"})

    with sanitizer.expect_violations("pack_frames") as seen:
        t = threading.Thread(target=on_fake_runtime, name="lah-runtime-seed")
        t.start()
        t.join()
    assert out["parts"]  # frame still produced
    hits = [v for v in seen if v["site"] == "pack_frames"]
    assert hits, f"seeded runtime-thread pack_frames not detected: {seen}"


def test_allowed_scope_suppresses_and_is_thread_local():
    """``sanitizer.allowed(site)`` silences exactly that site, exactly in
    scope — the runtime twin of the lint suppression annotation."""
    from learning_at_home_tpu.utils.serialization import EncodedBatch

    x = np.ones((4, 4), np.float32)

    async def encode_allowed():
        with sanitizer.allowed("EncodedBatch.encode"):
            return EncodedBatch.encode(x, "u8")

    with sanitizer.expect_violations() as seen:
        asyncio.run(encode_allowed())
    assert not seen, f"allowed() scope did not suppress: {seen}"

    async def encode_after_scope():
        return EncodedBatch.encode(x, "u8")

    with sanitizer.expect_violations() as seen:
        asyncio.run(encode_after_scope())
    assert seen, "check must re-arm once the allowed() scope exits"


def test_expect_violations_site_filter_keeps_unrelated():
    """A scoped drain must only swallow the sites the test seeded — a
    genuine violation from an unrelated site during the scope stays
    visible to the guard/summary instead of vanishing as 'expected'."""
    from learning_at_home_tpu.utils.serialization import EncodedBatch

    x = np.ones((4, 4), np.float32)

    async def bad():
        return EncodedBatch.encode(x, "u8")

    with sanitizer.expect_violations() as outer:  # test-hygiene drain
        with sanitizer.expect_violations("some.other.site") as inner:
            asyncio.run(bad())
        assert not inner, "filtered scope must not capture unrelated sites"
        assert any(
            v["site"] == "EncodedBatch.encode"
            for v in sanitizer.violations()
        ), "the genuine violation must survive the filtered drain"
    assert any(v["site"] == "EncodedBatch.encode" for v in outer)


# ---------------------------------------------------------------------------
# lock-order cycle detector
# ---------------------------------------------------------------------------


def test_seeded_lock_cycle_trips():
    """Thread 1 takes A→B, thread 2 takes B→A: the classic deadlock
    shape must be flagged from the ORDER GRAPH alone — the two threads
    here run sequentially, no actual deadlock is ever at risk."""
    a = sanitizer.lock("seeded.A")
    b = sanitizer.lock("seeded.B")

    def ab():
        with a:
            with b:
                pass

    def ba():
        with b:
            with a:
                pass

    with sanitizer.expect_violations("seeded.") as seen:
        t1 = threading.Thread(target=ab)
        t1.start()
        t1.join()
        t2 = threading.Thread(target=ba)
        t2.start()
        t2.join()
    cycles = [v for v in seen if v["kind"] == "lock-cycle"]
    assert cycles, f"seeded A->B/B->A cycle not detected: {seen}"
    assert "seeded.A" in cycles[0]["site"] and "seeded.B" in cycles[0]["site"]
    edges = sanitizer.lock_edges()
    assert ("seeded.A", "seeded.B") in edges
    assert ("seeded.B", "seeded.A") in edges


def test_seeded_same_name_instance_nesting_trips():
    """Two *different instances* of one lock class nested on one thread:
    name-level edges cannot order instances, so this ABBA-within-a-class
    shape is flagged directly (another thread nesting them the other way
    around would deadlock)."""
    e1 = sanitizer.lock("seeded.expert_state")
    e2 = sanitizer.lock("seeded.expert_state")

    with sanitizer.expect_violations("seeded.") as seen:
        with e1:
            with e2:
                pass
    hits = [
        v for v in seen
        if v["kind"] == "lock-cycle" and "instances nested" in v["detail"]
    ]
    assert hits, f"cross-instance same-name nesting not detected: {seen}"
    # reentrant re-acquire of the SAME instance stays clean
    r = sanitizer.lock("seeded.reentrant", reentrant=True)
    with sanitizer.expect_violations("seeded.") as seen:
        with r:
            with r:
                pass
    assert not seen, f"reentrant same-instance acquire false-flagged: {seen}"


def test_consistent_lock_order_is_clean():
    """Same nesting order on every thread: edges recorded, no cycle."""
    c = sanitizer.lock("seeded.C")
    d = sanitizer.lock("seeded.D")

    def cd():
        with c:
            with d:
                pass

    with sanitizer.expect_violations("seeded.") as seen:
        threads = [threading.Thread(target=cd) for _ in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    assert not [v for v in seen if v["kind"] == "lock-cycle"]
    assert ("seeded.C", "seeded.D") in sanitizer.lock_edges()


# ---------------------------------------------------------------------------
# event-loop stall detector
# ---------------------------------------------------------------------------


def test_seeded_loop_stall_is_recorded():
    """A callback holding a loop past LAH_SANITIZE_STALL_MS (default
    100 ms) must be counted, with the live stack captured mid-stall by
    the monitor thread.  Stalls are diagnostics, not violations — the
    conftest guard does not fail on them."""
    before = sanitizer.stall_stats()

    async def stall():
        time.sleep(0.3)  # deliberate: blocks this loop's only thread

    asyncio.run(stall())
    time.sleep(0.05)  # let the monitor's record land
    after = sanitizer.stall_stats()
    assert after["count"] > before["count"], (
        f"seeded 300 ms stall not recorded: {before} -> {after}"
    )
    assert after["max_ms"] >= 200.0
    last = after["last"]
    assert last is not None
    # the monitor captured the blocked frame: the seeded sleep is in it
    if last.get("stack"):
        assert "time.sleep(0.3)" in last["stack"] or "stall" in last["stack"]


# ---------------------------------------------------------------------------
# BackgroundLoop self-deadlock guard (R2's runtime twin — always on)
# ---------------------------------------------------------------------------


def test_background_loop_run_from_own_thread_raises():
    """``BackgroundLoop.run()`` from the loop's own thread is a
    guaranteed self-deadlock (the io_callback hang shape): the guard
    must raise instead of hanging, sanitizer on or off."""
    bg = BackgroundLoop(name="lah-loop-guard-test")
    try:

        async def noop():
            return 42

        async def call_run_from_loop():
            # we ARE the loop thread here: .run() would block forever
            with pytest.raises(RuntimeError, match="self-deadlock"):
                bg.run(noop())
            return "guarded"

        assert bg.run(call_run_from_loop(), timeout=10) == "guarded"
        # and from a host thread the same call works fine
        assert bg.run(noop(), timeout=10) == 42
    finally:
        bg.shutdown()


def test_site_stats_record_thread_classes():
    """site_stats is the observable the replaced monkeypatch tests assert
    on: it must bucket calls by thread class."""
    from learning_at_home_tpu.utils.serialization import EncodedBatch

    before = sanitizer.site_stats().get("EncodedBatch.encode", {})
    EncodedBatch.encode(np.ones((2, 2), np.float32), "u8")  # host thread
    after = sanitizer.site_stats().get("EncodedBatch.encode", {})
    assert after.get("host", 0) == before.get("host", 0) + 1
