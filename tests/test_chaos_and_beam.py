"""M4 tests: beam-search routing on larger grids + chaos (latency,
stragglers, drops) against the k-of-n quorum — [BJ] config 4 scaled to CI."""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from learning_at_home_tpu.client import reset_client_rpc
from learning_at_home_tpu.client.moe import RemoteMixtureOfExperts
from learning_at_home_tpu.client.routing import StaticExpertSource, beam_search_alive
from learning_at_home_tpu.dht import DHT
from learning_at_home_tpu.server import ChaosConfig, background_server

HID = 16


def test_beam_search_alive_static_source():
    import asyncio

    # 4x4 grid, only some rows alive
    experts = {f"g.{i}.{j}": ("h", 1) for i in (0, 2) for j in range(4)}
    source = StaticExpertSource(experts)
    logits0 = np.zeros((2, 4), np.float32)
    logits0[0, 2] = 5.0  # sample 0 prefers row 2
    logits0[1, 0] = 5.0  # sample 1 prefers row 0
    logits1 = np.zeros((2, 4), np.float32)
    alive = asyncio.run(
        beam_search_alive(source, "g", [logits0, logits1], (4, 4), beam_size=1)
    )
    assert set(alive) == {f"g.{i}.{j}" for i in (0, 2) for j in range(4)}


class _CountingSource:
    """ExpertSource wrapper counting DHT record reads (one per prefix key)."""

    def __init__(self, inner):
        self.inner = inner
        self.reads = 0

    async def get_alive_experts(self, prefix):
        self.reads += 1
        return await self.inner.get_alive_experts(prefix)

    async def first_k_active(self, prefixes, k):
        self.reads += len(prefixes)
        return await self.inner.first_k_active(prefixes, k)


def test_beam_search_3d_walks_dimensions():
    """Deep grids are walked dimension-by-dimension with per-level pruning:
    record reads stay O(beam·dims) on a 16x16x16 grid (4096 experts),
    and an expert whose prefix chain tops every level is always found."""
    import asyncio

    rs = np.random.RandomState(3)
    grid = (16, 16, 16)
    # 40 alive experts at random coords + one at the known argmax chain
    experts = {
        f"d3.{rs.randint(16)}.{rs.randint(16)}.{rs.randint(16)}": ("h", 1)
        for _ in range(40)
    }
    experts["d3.5.9.13"] = ("h", 2)
    source = _CountingSource(StaticExpertSource(experts))

    batch, beam = 4, 4
    logits = [rs.randn(batch, g).astype(np.float32) for g in grid]
    logits[0][0, 5] = 10.0  # sample 0's chain tops every level
    logits[1][0, 9] = 10.0
    logits[2][0, 13] = 10.0
    alive = asyncio.run(beam_search_alive(source, "d3", logits, grid, beam))

    assert "d3.5.9.13" in alive and alive["d3.5.9.13"] == ("h", 2)
    assert set(alive) <= set(experts)  # never invents uids
    # per-level budget: union_cap = 4*beam candidates at each of the two
    # walked levels (first_k_active at depth 1, row fetches at depth 2)
    assert source.reads <= 2 * 4 * beam + batch * beam, source.reads
    # far below enumerating the 256 depth-2 rows or 4096 uids
    assert source.reads < 64


def test_beam_search_2d_dead_top_rows_reroutes():
    """2-D grids reroute too: dead leaf rows trigger the one-shot capped
    retry over the remaining first-dimension rows."""
    import asyncio

    grid = (8, 4)
    experts = {"r2.6.1": ("h", 9)}  # only row 6 has anything alive
    source = _CountingSource(StaticExpertSource(experts))
    logits = [np.zeros((2, g), np.float32) for g in grid]
    logits[0][:, 0] = 10.0  # both samples prefer (dead) row 0
    logits[0][:, 6] = -5.0
    alive = asyncio.run(beam_search_alive(source, "r2", logits, grid, beam_size=2))
    assert set(alive) == {"r2.6.1"}


def test_beam_search_3d_dead_top_rows_reroutes():
    """If every top-scoring first-dimension row is dead, the walk rescans
    dimension 0 instead of returning empty (dead rows divert, not end)."""
    import asyncio

    grid = (8, 4, 4)
    experts = {"r.6.1.2": ("h", 9)}  # only row 6 has anything alive
    source = _CountingSource(StaticExpertSource(experts))
    batch = 2
    logits = [np.zeros((batch, g), np.float32) for g in grid]
    logits[0][:, 0] = 10.0  # both samples prefer (dead) row 0
    logits[0][:, 6] = -5.0  # alive row scores worst
    alive = asyncio.run(beam_search_alive(source, "r", logits, grid, beam_size=2))
    assert set(alive) == {"r.6.1.2"}


def test_beam_routing_matches_enumeration_on_dht():
    """With all rows alive and beam covering them, beam == enumerate."""
    dht = DHT()
    try:
        # 2-D grid of 8 experts on one server
        import optax

        from learning_at_home_tpu.models import make_expert
        from learning_at_home_tpu.server import ExpertBackend, Server

        experts = {}
        for i in range(4):
            for j in range(2):
                uid = f"grid.{i}.{j}"
                apply_fn, params = make_expert(
                    "ffn", HID, jax.random.PRNGKey(i * 2 + j), jnp.zeros((2, HID))
                )
                experts[uid] = ExpertBackend(uid, apply_fn, params, optax.sgd(0.01))
        server = Server(experts, host="127.0.0.1", dht=dht, update_period=0.5)
        server.run_in_background()
        try:
            deadline = time.time() + 10
            while time.time() < deadline:
                alive = dht._loop.run(dht._get_alive("grid"))
                if len(alive) == 8:
                    break
                time.sleep(0.1)
            assert len(alive) == 8

            x = jnp.asarray(np.random.RandomState(0).randn(4, HID).astype(np.float32))
            outs = {}
            for routing in ("enumerate", "beam"):
                moe = RemoteMixtureOfExperts(
                    in_features=HID, grid_size=(4, 2), uid_prefix="grid",
                    source=dht, k_best=2, k_min=1, routing=routing, beam_size=4,
                )
                gate = moe.init_gate_params(jax.random.PRNGKey(7))
                outs[routing] = np.asarray(moe(x, gate))
            np.testing.assert_allclose(
                outs["beam"], outs["enumerate"], atol=1e-5, rtol=1e-5
            )
        finally:
            server.shutdown()
    finally:
        dht.shutdown()
        reset_client_rpc()


def test_beam_routing_1d_grid_on_dht():
    """1-D grids have no intermediate prefix level: beam search queries the
    full-uid records directly (regression: used to find zero alive)."""
    dht = DHT()
    try:
        dht.declare_experts_sync(
            ["solo.0", "solo.1", "solo.2"], ("10.0.0.9", 1234), expiration=30
        )
        import asyncio

        logits = [np.asarray([[3.0, 1.0, 2.0]], np.float32)]
        alive = asyncio.run(
            beam_search_alive(dht, "solo", logits, (3,), beam_size=2)
        )
        # top-2 rows for the one sample are uids 0 and 2
        assert set(alive) == {"solo.0", "solo.2"}
        assert alive["solo.0"] == ("10.0.0.9", 1234)
    finally:
        dht.shutdown()


def test_partial_checkpoint_invisible(tmp_path):
    """A crash mid-save must not surface a partial step as 'latest'."""
    from learning_at_home_tpu.utils.checkpoint import (
        latest_step,
        mark_step_complete,
        save_pytree,
    )
    import jax.numpy as jnp

    root = str(tmp_path / "ck")
    save_pytree(root, 5, "params", {"a": jnp.ones(2)})
    # no marker: the "crash" happened before opt_state was written
    assert latest_step(root) is None
    mark_step_complete(root, 5)
    assert latest_step(root) == 5


def test_quorum_under_latency_and_stragglers():
    """Injected jitter + stragglers: quorum returns without waiting for the
    stragglers (grace timeout), throughput degrades gracefully."""
    chaos = ChaosConfig(
        base_latency=0.01, jitter=0.02, straggler_prob=0.3,
        straggler_delay=1.5, seed=42,
    )
    with background_server(
        num_experts=4, hidden_dim=HID, expert_prefix="ffn", seed=5, chaos=chaos
    ) as (endpoint, srv):
        source = StaticExpertSource({uid: endpoint for uid in srv.experts})
        moe = RemoteMixtureOfExperts(
            in_features=HID, grid_size=(4,), uid_prefix="ffn", source=source,
            k_best=4, k_min=1, timeout_after_k_min=0.15, forward_timeout=5.0,
        )
        gate = moe.init_gate_params(jax.random.PRNGKey(0))
        x = jnp.asarray(np.random.RandomState(1).randn(4, HID).astype(np.float32))
        t0 = time.monotonic()
        out = np.asarray(moe(x, gate))
        elapsed = time.monotonic() - t0
        assert np.isfinite(out).all()
        # must NOT have waited for all stragglers (1.5s each, serial worst
        # case >> grace); quorum+grace bounds the wait
        assert elapsed < 1.5 + 1.0, f"took {elapsed}s — straggler not dropped?"
        assert srv.chaos.injected_delays + srv.chaos.injected_stragglers > 0
    reset_client_rpc()


def test_quorum_under_drops():
    """Reply drops look like timeouts; k_min=1 still succeeds eventually."""
    chaos = ChaosConfig(drop_prob=0.4, seed=7)
    with background_server(
        num_experts=4, hidden_dim=HID, expert_prefix="ffn", seed=6, chaos=chaos
    ) as (endpoint, srv):
        source = StaticExpertSource({uid: endpoint for uid in srv.experts})
        moe = RemoteMixtureOfExperts(
            in_features=HID, grid_size=(4,), uid_prefix="ffn", source=source,
            k_best=4, k_min=1, timeout_after_k_min=0.1, forward_timeout=1.0,
        )
        gate = moe.init_gate_params(jax.random.PRNGKey(0))
        x = jnp.asarray(np.random.RandomState(2).randn(3, HID).astype(np.float32))
        out = np.asarray(moe(x, gate))
        assert np.isfinite(out).all()
        assert srv.chaos.injected_drops > 0
    reset_client_rpc()
