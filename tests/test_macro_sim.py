"""Macro-sim (ISSUE 18): virtual clock + seams, trace parsing/sampling,
whole-swarm byte-determinism, the real-code-under-sim proof, and the
512-expert placement stress on a clustered topology.

The determinism contract under test: ``run_macro_sim`` with the same
(seed, trace, topology) produces byte-identical canonical report JSON —
across repeated runs in one process AND across processes (the DHT's
entropy seam and every clock seam are virtualized; nothing reads the
wall clock, ``os.urandom`` or hash-salted iteration order on the sim
path).
"""

import asyncio
import json
import time

import pytest

from learning_at_home_tpu.sim.clock import (
    SEAMS,
    VirtualClock,
    WallClock,
    installed_clock,
    installed_entropy,
    run_virtual,
)
from learning_at_home_tpu.sim.runner import canonical_json, run_macro_sim
from learning_at_home_tpu.sim.trace import (
    ChurnEvent,
    churn_rounds,
    parse_churn,
    parse_segments,
    parse_trace,
)

import random


# one small scenario shared by the determinism / invariant / proof tests
# (module-level cache: the report is pure per config, re-running it per
# test would only burn suite budget)
TINY = dict(
    nodes=24, servers=6, gateways=2, experts=8,
    trace="poisson:40:2,burst:150:1", churn="1.5:kill:0.34",
    slots=16, lookup_period_s=0.5, placement_period_s=2.0,
    join_batch=8,
)
_reports: dict = {}


def tiny_report(seed: int = 0, fresh: bool = False) -> dict:
    if fresh or seed not in _reports:
        report = run_macro_sim(seed=seed, **TINY)
        if fresh:
            return report
        _reports[seed] = report
    return _reports[seed]


# ---------------------------------------------------------------- clock


def test_virtual_clock_surfaces():
    clk = VirtualClock(step=0.5, start=10.0)
    assert clk.monotonic() == 10.0          # read does not advance
    assert clk() == 10.5                    # call advances (verify.py shape)
    clk.advance(2.0)
    assert clk.monotonic() == 12.5
    clk.advance(-5.0)                       # monotonic by construction
    assert clk.monotonic() == 12.5
    clk.sleep(0.5)
    assert clk.monotonic() == 13.0
    assert clk.time() == clk.epoch + 13.0


def test_wall_clock_same_surface():
    w = WallClock()
    assert w.monotonic() <= w.monotonic()
    assert w.time() > 1_000_000_000
    assert callable(w.sleep)


def test_installed_clock_patches_and_restores_every_seam():
    import importlib

    clk = VirtualClock(step=0.0, start=42.0)
    originals = {}
    for mod_name, attr, _method in SEAMS:
        mod = importlib.import_module(mod_name)
        originals[(mod_name, attr)] = getattr(mod, attr)
    with installed_clock(clk):
        for mod_name, attr, method in SEAMS:
            mod = importlib.import_module(mod_name)
            assert getattr(mod, attr) == getattr(clk, method), (
                f"{mod_name}.{attr} not patched"
            )
        from learning_at_home_tpu.utils.timed_storage import get_dht_time
        assert get_dht_time() == clk.epoch + 42.0
    for (mod_name, attr), orig in originals.items():
        mod = importlib.import_module(mod_name)
        assert getattr(mod, attr) is orig, f"{mod_name}.{attr} not restored"


def test_installed_entropy_seeds_dht_ids():
    from learning_at_home_tpu.dht import routing as dht_routing

    orig = dht_routing._urandom
    with installed_entropy(random.Random(7)):
        a = dht_routing.DHTID.generate()
    with installed_entropy(random.Random(7)):
        b = dht_routing.DHTID.generate()
    assert a == b                           # seeded: reproducible
    assert dht_routing._urandom is orig     # restored


def test_virtual_event_loop_advances_without_wall_time():
    async def scenario():
        t0 = asyncio.get_running_loop().time()
        await asyncio.sleep(120.0)
        await asyncio.gather(asyncio.sleep(30.0), asyncio.sleep(45.0))
        return asyncio.get_running_loop().time() - t0

    clk = VirtualClock(step=0.0)
    wall0 = time.monotonic()
    elapsed_virtual = run_virtual(scenario(), clock=clk)
    wall = time.monotonic() - wall0
    assert elapsed_virtual == pytest.approx(165.0)
    assert clk.now == pytest.approx(165.0)
    assert wall < 5.0                       # hours-per-second, not 1:1


def test_virtual_event_loop_flags_deadlock():
    async def stuck():
        await asyncio.Event().wait()        # nothing will ever set it

    with pytest.raises(RuntimeError, match="virtual-time deadlock"):
        run_virtual(stuck())


# ---------------------------------------------------------------- trace


def test_parse_segments_grammar():
    segs = parse_segments("poisson:10:5, burst:100:2,diurnal:20:60:0.5:30")
    assert [s.kind for s in segs] == ["poisson", "burst", "diurnal"]
    assert segs[2].peak_rate_hz == pytest.approx(30.0)
    assert segs[2].rate_at(30.0 / 4) == pytest.approx(30.0)  # sin peak
    with pytest.raises(ValueError):
        parse_segments("poisson:10")        # missing duration
    with pytest.raises(ValueError):
        parse_segments("diurnal:20:60:1.5:30")  # depth out of range
    with pytest.raises(ValueError):
        parse_segments("")                  # empty spec
    with pytest.raises(ValueError):
        parse_segments("warp:1:2")          # unknown kind


def test_parse_churn_grammar_and_ordering():
    events = parse_churn("60:join:4, 35:kill:0.1")
    assert [e.kind for e in events] == ["kill", "join"]  # time-sorted
    assert events[0].fraction == pytest.approx(0.1)
    assert events[1].count == 4
    with pytest.raises(ValueError):
        parse_churn("10:kill:1.5")          # fraction > 1
    with pytest.raises(ValueError):
        parse_churn("10:resize:3")          # unknown kind
    assert churn_rounds(3, 0.1, every_s=2.0) == (
        ChurnEvent(0.0, "kill", fraction=0.1),
        ChurnEvent(2.0, "kill", fraction=0.1),
        ChurnEvent(4.0, "kill", fraction=0.1),
    )


def test_trace_arrivals_seeded_and_rate_accurate():
    trace = parse_trace("poisson:50:20,diurnal:30:40:0.5:10")
    a = list(trace.iter_arrivals(random.Random(3)))
    b = list(trace.iter_arrivals(random.Random(3)))
    c = list(trace.iter_arrivals(random.Random(4)))
    assert a == b                           # seeded: identical stream
    assert a != c                           # seed moves the timings
    assert a == sorted(a)                   # in order, within bounds
    assert 0.0 < a[0] and a[-1] < trace.duration_s
    # expectation 50*20 + 30*40 = 2200; thinning should land close
    assert 1900 < len(a) < 2500
    # the burst segment really concentrates arrivals
    burst = parse_trace("poisson:10:10,burst:200:1")
    times = list(burst.iter_arrivals(random.Random(0)))
    in_burst = sum(1 for t in times if t >= 10.0)
    assert in_burst > len(times) // 2


# ------------------------------------------------- whole-swarm determinism


def test_macro_sim_report_byte_deterministic():
    first = canonical_json(tiny_report(seed=0))
    again = canonical_json(tiny_report(seed=0, fresh=True))
    assert first == again


def test_macro_sim_seed_changes_timings_not_invariants():
    base = tiny_report(seed=0)
    other = tiny_report(seed=1)
    assert canonical_json(base) != canonical_json(other)
    # arrival timings / swarm construction genuinely moved ...
    assert (
        base["virtual_duration_s"] != other["virtual_duration_s"]
        or base["swarm"]["join_mean_ms"] != other["swarm"]["join_mean_ms"]
    )
    # ... but the invariant outcomes hold at every seed
    for rep in (base, other):
        tr = rep["traffic"]
        assert tr["arrivals"] > 0
        assert tr["completed"] + tr["shed"] + tr["errored"] == tr["arrivals"]
        assert tr["errored"] == 0
        assert tr["completed"] > 0 and tr["tokens_served"] > 0
        assert rep["swarm"]["join_failures"] == 0
        assert rep["swarm"]["killed"] > 0            # churn really fired
        assert rep["dht"]["lookups"] > 0
        assert rep["routing"]["selection_rounds"] > 0
        assert rep["routing"]["bias_applied"] > 0    # cost model engaged
        assert rep["config"]["trace"]["duration_s"] == pytest.approx(3.0)


def test_macro_sim_report_carries_no_wall_values():
    rep = tiny_report(seed=0)
    # every float in the report is virtual-time- or seed-derived; a wall
    # reading would show up as a huge monotonic timestamp
    def walk(obj):
        if isinstance(obj, dict):
            for v in obj.values():
                walk(v)
        elif isinstance(obj, list):
            for v in obj:
                walk(v)
        elif isinstance(obj, float):
            assert obj < 1e8, f"suspicious wall-sized value {obj}"
    walk(rep)
    json.dumps(rep)  # JSON-serializable throughout


# ------------------------------------------------- real code under sim


def test_sim_runs_real_admission_code(monkeypatch):
    """Patch the REAL gateway admission invariant and watch the sim
    report change: if the sim reimplemented admission instead of calling
    ``AdmissionController.admit``, this patch could not turn every
    arrival into a shed."""
    from learning_at_home_tpu.gateway.admission import AdmissionController

    def shed_everything(self, pages_needed=0):
        self.shed_total += 1
        return False, 1.0, "patched: always shed"

    monkeypatch.setattr(AdmissionController, "admit", shed_everything)
    rep = run_macro_sim(seed=0, **TINY)
    assert rep["traffic"]["shed"] == rep["traffic"]["arrivals"] > 0
    assert rep["traffic"]["completed"] == 0
    assert rep["traffic"]["tokens_served"] == 0


def test_sim_runs_real_routing_cost_code(monkeypatch):
    """Same proof for the routing stack: disable the real cost model's
    bias and the report's routing section must go dark."""
    from learning_at_home_tpu.client.routing import RoutingCostModel

    monkeypatch.setattr(
        RoutingCostModel, "bias", lambda self, *a, **kw: None
    )
    rep = run_macro_sim(seed=0, **TINY)
    assert rep["routing"]["bias_applied"] == 0
    assert rep["routing"]["selection_rounds"] > 0
    baseline = tiny_report(seed=0)
    assert baseline["routing"]["bias_applied"] > 0


# ------------------------------------------------- placement stress (512)


def clustered_stress_snapshot(n_experts=512, n_nodes=256, seed=0):
    """512 experts, 2 per node, every node FULL (capacity 2), and each
    co-activation pair split across anti-podal nodes — the topology
    where every profitable single move is capacity-blocked and only the
    swap neighborhood can reunite pairs."""
    from learning_at_home_tpu.sim.serving import LinkModel

    lm = LinkModel(seed, n_clusters=4)
    nodes = [f"10.0.0.{i // 250}:{31000 + i}" for i in range(n_nodes)]
    half = n_nodes // 2
    experts, coact = {}, {}
    for i in range(n_nodes):
        # node i: a_i  +  b_{partner};  partner(i) = i + half (mod n)
        experts[f"a.{i:03d}"] = nodes[i]
        experts[f"b.{i:03d}"] = nodes[(i + half) % n_nodes]
        key = (f"a.{i:03d}", f"b.{i:03d}")
        coact[f"{key[0]}|{key[1]}"] = 50
    links = {}
    for i in range(n_nodes):
        j = (i + half) % n_nodes
        rtt, bw = lm.link(31000 + i, 31000 + j)
        links.setdefault(nodes[i], {})[nodes[j]] = [rtt, bw]
    return {
        "experts": experts,
        "activations": {uid: 1 for uid in experts},
        "coact": coact,
        "links": links,
        "capacity": {n: 2 for n in nodes},
    }


def test_placement_stress_512_experts_deterministic_and_swaps_win():
    from learning_at_home_tpu.analysis.placement import plan_to_json, solve

    snap = clustered_stress_snapshot()
    assert len(snap["experts"]) == 512 and len(snap["capacity"]) == 256

    both_1 = solve(snap, seed=11, max_moves=32, max_rounds=2)
    both_2 = solve(snap, seed=11, max_moves=32, max_rounds=2)
    assert plan_to_json(both_1) == plan_to_json(both_2)  # byte-stable

    move_only = solve(
        snap, seed=11, max_moves=32, max_rounds=2, neighborhoods=("move",)
    )
    # every node is full: no single move is capacity-legal
    assert move_only["moves"] == []
    assert move_only["cost_after"] == move_only["cost_before"]
    # pair swaps reunite co-activating pairs under the same caps
    assert both_1["moves"], "swap neighborhood produced no moves"
    assert both_1["cost_after"] < move_only["cost_after"]
    assert all(
        m["uid"].startswith(("a.", "b.")) for m in both_1["moves"]
    )


def test_solve_neighborhoods_default_unchanged():
    """The new kwarg must not disturb the default plan bytes (the
    collect-gate placement stage diffs solver output across runs)."""
    from learning_at_home_tpu.analysis.placement import plan_to_json, solve

    snap = clustered_stress_snapshot(n_nodes=16)
    assert plan_to_json(solve(snap, seed=3)) == plan_to_json(
        solve(snap, seed=3, neighborhoods=("move", "swap"))
    )
