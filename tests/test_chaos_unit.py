"""Unit tests for the chaos injector itself (deterministic under a seed)."""

import asyncio
import time

from learning_at_home_tpu.server.chaos import ChaosConfig


def run(coro):
    return asyncio.run(coro)


def test_drop_probability_and_counters():
    inj = ChaosConfig(drop_prob=0.5, seed=1).make()

    async def main():
        delivered = 0
        for _ in range(200):
            delivered += await inj.before_reply()
        return delivered

    delivered = run(main())
    assert 60 < delivered < 140  # ~binomial(200, 0.5)
    assert inj.injected_drops == 200 - delivered


def test_deterministic_under_seed():
    async def outcomes(seed):
        inj = ChaosConfig(drop_prob=0.3, straggler_prob=0.2, straggler_delay=0.0, seed=seed).make()
        return [await inj.before_reply() for _ in range(50)]

    a = run(outcomes(7))
    b = run(outcomes(7))
    c = run(outcomes(8))
    assert a == b
    assert a != c  # different seed, different trace (overwhelmingly likely)


def test_latency_and_straggler_delays():
    async def main():
        inj = ChaosConfig(base_latency=0.02, jitter=0.0, seed=0).make()
        t0 = time.monotonic()
        assert await inj.before_reply()
        base_elapsed = time.monotonic() - t0
        assert base_elapsed >= 0.02
        assert inj.injected_delays == 1

        stall = ChaosConfig(straggler_prob=1.0, straggler_delay=0.05, seed=0).make()
        t0 = time.monotonic()
        assert await stall.before_reply()
        assert time.monotonic() - t0 >= 0.05
        assert stall.injected_stragglers == 1

    run(main())


def test_noop_config_is_instant():
    async def main():
        inj = ChaosConfig().make()
        t0 = time.monotonic()
        for _ in range(100):
            assert await inj.before_reply()
        assert time.monotonic() - t0 < 0.5
        assert inj.injected_delays == inj.injected_drops == 0

    run(main())


def test_bandwidth_model_delays_proportionally_to_bytes():
    """bandwidth_bps: reply delayed by nbytes/bw — the knob that makes
    payload size (and wire compression) visible on loopback."""
    async def main():
        inj = ChaosConfig(bandwidth_bps=1e6).make()  # 1 MB/s
        t0 = time.monotonic()
        assert await inj.before_reply(nbytes=100_000)  # -> 0.1 s
        dt_big = time.monotonic() - t0
        t0 = time.monotonic()
        assert await inj.before_reply(nbytes=10_000)  # -> 0.01 s
        dt_small = time.monotonic() - t0
        assert dt_big >= 0.09
        assert dt_small < dt_big
        # nbytes default (0) adds nothing
        t0 = time.monotonic()
        assert await inj.before_reply()
        assert time.monotonic() - t0 < 0.05

    run(main())
