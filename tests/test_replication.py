"""Hedged replica dispatch + replica lifecycle (ISSUE 8).

Real localhost servers, fake (static) expert sources: two servers host
the SAME uid (``Server.create(expert_uids=...)`` crc32-seeds identical
params on both), the alive map carries a replica SET, and the dispatch
fan-out must (a) hedge only past the RTT-EMA-derived deadline, (b) take
the first successful reply and cancel the loser with the right marker
semantics (straggler-marked primary folds its EMA; race-losing backup
never does), (c) never hedge a backward, and (d) survive a primary kill
mid-training with zero dropped samples and < 1 round of quality cost.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from learning_at_home_tpu.client import reset_client_rpc
from learning_at_home_tpu.client.moe import RemoteMixtureOfExperts
from learning_at_home_tpu.client.routing import (
    DEFAULT_COST_WEIGHT,
    StaticExpertSource,
)
from learning_at_home_tpu.client.rpc import pool_registry
from learning_at_home_tpu.server import ChaosConfig
from learning_at_home_tpu.server.server import background_server

HID = 16


@pytest.fixture(autouse=True)
def _fresh_client_state():
    """Every test seeds pool RTT EMAs by hand — never leak them."""
    yield
    reset_client_rpc()


def _replicated_moe(ep_a, ep_b, **kw):
    """One expert ``hdg.0`` hosted by BOTH endpoints (a is listed first;
    the cost model re-orders by predicted cost at dispatch time)."""
    source = StaticExpertSource({"hdg.0": (ep_a, ep_b)})
    kw.setdefault("forward_timeout", 20.0)
    kw.setdefault("hedge_floor_s", 0.05)
    return RemoteMixtureOfExperts(
        in_features=HID, grid_size=(1,), uid_prefix="hdg", source=source,
        k_best=1, k_min=1, **kw,
    )


def _seed_rtt(ep, rtt):
    pool = pool_registry().get(ep)
    pool.rtt_ema = rtt
    return pool


def _x(rows=4, seed=0):
    return jnp.asarray(
        np.random.RandomState(seed).randn(rows, HID).astype(np.float32)
    )


def _replica_pair(chaos_a=None, chaos_b=None):
    return (
        background_server(
            hidden_dim=HID, expert_uids=["hdg.0"], optimizer=optax.sgd(0.0),
            chaos=chaos_a,
        ),
        background_server(
            hidden_dim=HID, expert_uids=["hdg.0"], optimizer=optax.sgd(0.0),
            chaos=chaos_b,
        ),
    )


def test_hedge_fires_only_past_deadline():
    """Fast primary, generous deadline (3 × seeded 1 s EMA): the hedge
    never arms, and the replica set is still visible in the stats."""
    ctx_a, ctx_b = _replica_pair()
    with ctx_a as (ep_a, _), ctx_b as (ep_b, _):
        moe = _replicated_moe(ep_a, ep_b)
        _seed_rtt(ep_a, 1.0)   # deadline max(3 × 1.0, 0.05) = 3 s
        _seed_rtt(ep_b, 2.0)   # orders second → a is the primary
        gate = moe.init_gate_params(jax.random.PRNGKey(0))
        for i in range(3):
            jax.block_until_ready(moe(_x(seed=i), gate))
        routing = moe.dispatch_stats()["routing"]
        assert routing["hedge_fires"] == 0, routing
        assert routing["hedge_wins"] == 0
        assert moe.samples_dropped == 0
        assert routing["replica_counts"] == {"hdg.0": 2}
        assert moe._headline_metrics()["lah_client_replicas_max"] == 2


def test_dead_primary_fast_failure_failover_wins():
    """The primary dies while still listed in the alive set: its calls
    fail fast, the backup replica is fired immediately (no deadline wait
    needed for a hard failure), and the reply is bitwise the healthy
    twin's — zero dropped samples, hedge-win counter > 0."""
    ctx_a, ctx_b = _replica_pair()
    with ctx_a as (ep_a, srv_a), ctx_b as (ep_b, _):
        moe = _replicated_moe(ep_a, ep_b)
        _seed_rtt(ep_a, 0.001)  # cheapest → stays primary after death
        _seed_rtt(ep_b, 0.5)
        gate = moe.init_gate_params(jax.random.PRNGKey(0))
        y0 = np.asarray(moe(_x(), gate))  # both alive (sgd(0.0): frozen)
        srv_a.shutdown()
        y1 = np.asarray(moe(_x(), gate))
        np.testing.assert_allclose(y1, y0, atol=1e-5)
        routing = moe.dispatch_stats()["routing"]
        assert routing["hedge_fires"] >= 1, routing
        assert routing["hedge_wins"] >= 1, routing
        assert moe.samples_dropped == 0


def test_hedge_win_cancels_straggler_primary_marked():
    """A SLOW (not dead) primary: the backup wins the race past the
    50 ms deadline, and the loser primary's cancel carries the straggler
    marker — its elapsed wait folds into its RTT EMA (the pool learns
    the slowness), per the QUORUM_STRAGGLER_CANCEL contract."""
    ctx_a, ctx_b = _replica_pair(
        chaos_a=ChaosConfig(base_latency=0.5, seed=0)
    )
    with ctx_a as (ep_a, _), ctx_b as (ep_b, _):
        moe = _replicated_moe(ep_a, ep_b)
        pool_a = _seed_rtt(ep_a, 0.001)  # deadline = floor = 0.05 s
        _seed_rtt(ep_b, 0.4)
        gate = moe.init_gate_params(jax.random.PRNGKey(0))
        jax.block_until_ready(moe(_x(), gate))
        routing = moe.dispatch_stats()["routing"]
        assert routing["hedge_fires"] == 1, routing
        assert routing["hedge_wins"] == 1, routing
        assert moe.samples_dropped == 0
        # marked cancel folded the ≥50 ms elapsed wait into the EMA:
        # 0.8 × 0.001 + 0.2 × ≥0.05 ≥ 0.0108 ≫ the seeded 0.001
        assert pool_a.rtt_ema > 0.005, pool_a.rtt_ema
        # the whole dispatch beat the primary's 0.5 s injected latency
        assert moe.dispatch_times[-1] < 0.45, list(moe.dispatch_times)


def test_hedge_loser_backup_ema_never_poisoned():
    """The primary answers AFTER the hedge fired but before the backup:
    the backup's cancel is UNMARKED, so its RTT EMA stays exactly the
    seeded value — a lost race is evidence about the race, not the
    peer."""
    ctx_a, ctx_b = _replica_pair(
        chaos_a=ChaosConfig(base_latency=0.15, seed=0),
        chaos_b=ChaosConfig(base_latency=5.0, seed=0),
    )
    with ctx_a as (ep_a, _), ctx_b as (ep_b, _):
        moe = _replicated_moe(ep_a, ep_b)
        _seed_rtt(ep_a, 0.001)  # deadline 0.05 s < the 0.15 s latency
        pool_b = _seed_rtt(ep_b, 0.4)
        gate = moe.init_gate_params(jax.random.PRNGKey(0))
        y = np.asarray(moe(_x(), gate))
        assert np.isfinite(y).all()
        routing = moe.dispatch_stats()["routing"]
        assert routing["hedge_fires"] == 1, routing
        assert routing["hedge_wins"] == 0, routing  # the primary won
        assert pool_b.rtt_ema == 0.4, pool_b.rtt_ema  # bitwise untouched
        assert moe.samples_dropped == 0


def test_hedge_mult_zero_disables_hedging():
    ctx_a, ctx_b = _replica_pair(
        chaos_a=ChaosConfig(base_latency=0.2, seed=0)
    )
    with ctx_a as (ep_a, _), ctx_b as (ep_b, _):
        moe = _replicated_moe(ep_a, ep_b, hedge_mult=0.0)
        _seed_rtt(ep_a, 0.001)
        _seed_rtt(ep_b, 0.4)
        gate = moe.init_gate_params(jax.random.PRNGKey(0))
        jax.block_until_ready(moe(_x(), gate))
        routing = moe.dispatch_stats()["routing"]
        assert routing["hedge_fires"] == 0, routing
        # it really waited out the slow primary instead of hedging
        assert moe.dispatch_times[-1] >= 0.15, list(moe.dispatch_times)


def test_backward_never_hedges():
    """Gradient fan-outs must not hedge — the server-side optimizer step
    is a side effect a duplicate request would apply twice.  The slow
    primary that makes every FORWARD hedge leaves the backward counters
    untouched (backups only ride the forward path)."""
    ctx_a, ctx_b = _replica_pair(
        chaos_a=ChaosConfig(base_latency=0.2, seed=0)
    )
    with ctx_a as (ep_a, _), ctx_b as (ep_b, _):
        moe = _replicated_moe(ep_a, ep_b)
        _seed_rtt(ep_a, 0.001)
        _seed_rtt(ep_b, 0.4)
        gate = moe.init_gate_params(jax.random.PRNGKey(0))

        def loss(g, x):
            return jnp.sum(moe(x, g) ** 2)

        g = jax.grad(loss)(gate, _x())
        assert all(np.isfinite(v).all() for v in jax.tree_util.tree_leaves(g))
        fires_after_step = moe.hedge_fires
        assert fires_after_step >= 1  # the forward hedged (slow primary)
        # the backward ran against the WINNER endpoint with no backup
        # armed: had it hedged, fires would exceed the forward's count
        assert moe.hedge_fires == fires_after_step
        assert moe.samples_dropped == 0


def test_replica_kill_mid_training_costs_less_than_one_round():
    """Tier-1 chaos variant of the churn scenario: kill the hot expert's
    primary replica mid-training.  Every post-kill step must succeed via
    the hedged fallback (zero failed dispatches, zero dropped samples)
    and the loss curve keeps improving — the kill costs < 1 round of
    quality, not a divergence."""
    with background_server(
        hidden_dim=HID, expert_uids=["hdg.0"], optimizer=optax.sgd(5e-2),
    ) as (ep_a, srv_a):
        with background_server(
            hidden_dim=HID, expert_uids=["hdg.0", "hdg.1"],
            optimizer=optax.sgd(5e-2),
        ) as (ep_b, _):
            # the hot expert hdg.0 is replicated; hdg.1 lives only on b
            source = StaticExpertSource(
                {"hdg.0": (ep_a, ep_b), "hdg.1": ep_b}
            )
            moe = RemoteMixtureOfExperts(
                in_features=HID, grid_size=(2,), uid_prefix="hdg",
                source=source, k_best=2, k_min=1, forward_timeout=20.0,
                hedge_floor_s=0.05,
            )
            _seed_rtt(ep_a, 0.001)  # the doomed primary for hdg.0
            _seed_rtt(ep_b, 0.4)
            gate = moe.init_gate_params(jax.random.PRNGKey(0))
            opt = optax.adam(5e-2)
            opt_state = opt.init(gate)
            rs = np.random.RandomState(0)
            x = jnp.asarray(rs.randn(8, HID).astype(np.float32))
            y = jnp.asarray(rs.randn(8, HID).astype(np.float32) * 0.1)

            def loss(g):
                return jnp.mean((moe(x, g) - y) ** 2)

            losses = []
            for step in range(8):
                if step == 4:
                    srv_a.shutdown()  # kill the primary mid-training
                val, grads = jax.value_and_grad(loss)(gate)
                updates, opt_state = opt.update(grads, opt_state)
                gate = optax.apply_updates(gate, updates)
                losses.append(float(val))
            routing = moe.dispatch_stats()["routing"]
            assert moe.samples_dropped == 0, (losses, routing)
            assert routing["hedge_wins"] >= 1, routing
            # quality: the step right after the kill regresses by no
            # more than one step's usual movement (the backup replica
            # missed the primary's last gradient steps — that gap IS
            # the < 1 round cost), and the curve still ends below its
            # pre-kill level
            pre_kill, post_kill = losses[3], losses[4]
            step_move = max(
                abs(losses[2] - losses[3]), abs(losses[1] - losses[2])
            )
            assert post_kill <= pre_kill + step_move + 1e-3, losses
            assert losses[-1] < losses[3], losses


def test_loss_parity_cost_model_bias_vs_blind():
    """Decode-gap guard (ROADMAP standing item): the cost-model bias at
    DEFAULT strength must not measurably degrade the smoke loss curve vs
    the bias=0 blind gate.  Two identical 4-expert swarms (same seeds →
    identical expert params), same data, same gate init; only the
    routing_cost_weight differs."""

    def run(weight):
        with background_server(
            num_experts=2, hidden_dim=HID, expert_prefix="par", seed=3,
            optimizer=optax.sgd(1e-2),
        ) as (ep_a, srv_a):
            with background_server(
                num_experts=2, hidden_dim=HID, expert_prefix="par",
                expert_offset=2, seed=3, optimizer=optax.sgd(1e-2),
            ) as (ep_b, srv_b):
                experts = {uid: ep_a for uid in srv_a.experts}
                experts.update({uid: ep_b for uid in srv_b.experts})
                moe = RemoteMixtureOfExperts(
                    in_features=HID, grid_size=(4,), uid_prefix="par",
                    source=StaticExpertSource(experts), k_best=2, k_min=1,
                    timeout_after_k_min=2.0, routing_cost_weight=weight,
                )
                gate = moe.init_gate_params(jax.random.PRNGKey(1))
                opt = optax.adam(5e-2)
                opt_state = opt.init(gate)
                rs = np.random.RandomState(7)
                x = jnp.asarray(rs.randn(8, HID).astype(np.float32))
                y = jnp.asarray(rs.randn(8, HID).astype(np.float32) * 0.1)

                def loss(g):
                    return jnp.mean((moe(x, g) - y) ** 2)

                losses = []
                for _ in range(6):
                    val, grads = jax.value_and_grad(loss)(gate)
                    updates, opt_state = opt.update(grads, opt_state)
                    gate = optax.apply_updates(gate, updates)
                    losses.append(float(val))
                applied = moe.dispatch_stats()["routing"]["bias_applied"]
        reset_client_rpc()
        return losses, applied

    blind, blind_applied = run(0.0)
    cost, cost_applied = run(DEFAULT_COST_WEIGHT)
    assert blind_applied == 0  # the A/B arm really is the blind gate
    assert cost_applied > 0    # and the cost arm really biased selection
    # parity: the biased arm's final loss is within noise of the blind
    # arm's (loopback peers are near-identical, so the bias should only
    # resolve near-ties, never distort the mixture measurably)
    assert cost[-1] <= blind[-1] + max(0.1 * abs(blind[-1]), 0.02), (
        blind, cost,
    )
    # both curves actually trained
    assert cost[-1] < cost[0] and blind[-1] < blind[0]


def test_add_replica_builds_identical_backend_and_serves():
    """Server-side lifecycle: an (initially empty) server grows a
    replica of a uid it never hosted via ``add_replica`` — the crc32-uid
    seeding means the replica's params are BITWISE the original
    hoster's, so a dispatch answered by either replica is the same
    mixture."""
    from learning_at_home_tpu.server.server import Server

    with background_server(
        hidden_dim=HID, expert_uids=["ar.0"], optimizer=optax.sgd(0.0),
    ) as (ep_a, srv_a):
        srv_b = Server.create(
            num_experts=0, hidden_dim=HID, host="127.0.0.1",
            optimizer=optax.sgd(0.0),
        )
        try:
            assert srv_b.add_replica("ar.0") is True
            assert srv_b.add_replica("ar.0") is False  # idempotent
            assert srv_b.replica_uids == {"ar.0"}
            assert srv_b._telemetry_extra()["replicas"] == ["ar.0"]
            pa = srv_a.experts["ar.0"].state_dict()["params"]
            pb = srv_b.experts["ar.0"].state_dict()["params"]
            for a, b in zip(
                jax.tree_util.tree_leaves(pa), jax.tree_util.tree_leaves(pb)
            ):
                np.testing.assert_array_equal(a, b)
            # and the replica actually serves: dispatch pinned to it
            moe = RemoteMixtureOfExperts(
                in_features=HID, grid_size=(1,), uid_prefix="ar",
                source=StaticExpertSource({"ar.0": srv_b.endpoint}),
                k_best=1, k_min=1, forward_timeout=20.0,
            )
            gate = moe.init_gate_params(jax.random.PRNGKey(0))
            y = np.asarray(moe(_x(), gate))
            assert np.isfinite(y).all()
            assert moe.samples_dropped == 0
        finally:
            srv_b.shutdown()


def test_replica_sync_converges_diverged_replicas():
    """Replicas of a TRAINING expert stay in sync through the existing
    averaging machinery (ReplicaSync → DecentralizedAverager butterfly
    all-reduce): two hosters whose params were deliberately diverged end
    a sync round with the group mean on BOTH sides."""
    import time

    from learning_at_home_tpu.dht import DHT
    from learning_at_home_tpu.server.server import Server

    boot = DHT()
    d_a = DHT(initial_peers=[boot.endpoint])
    d_b = DHT(initial_peers=[boot.endpoint])
    srv_a = srv_b = None
    try:
        srv_a = Server.create(
            expert_uids=["rs.0"], hidden_dim=HID, host="127.0.0.1",
            optimizer=optax.sgd(0.0), dht=d_a, update_period=1.0,
        )
        srv_b = Server.create(
            expert_uids=["rs.0"], hidden_dim=HID, host="127.0.0.1",
            optimizer=optax.sgd(0.0), dht=d_b, update_period=1.0,
        )
        # diverge b's copy: +1 on every leaf (as if it missed updates)
        b_backend = srv_b.experts["rs.0"]
        pa = srv_a.experts["rs.0"].state_dict()["params"]
        b_backend.replace_params(
            jax.tree_util.tree_map(
                lambda t: t + 1.0, b_backend.state_dict()["params"]
            )
        )
        sync_a = srv_a.enable_replica_sync("rs.0", period=0.5)
        sync_b = srv_b.enable_replica_sync("rs.0", period=0.5)
        assert srv_a.enable_replica_sync("rs.0") is sync_a  # idempotent
        deadline = time.time() + 30
        while time.time() < deadline:
            if sync_a.rounds >= 1 and sync_b.rounds >= 1:
                break
            time.sleep(0.2)
        assert sync_a.rounds >= 1 and sync_b.rounds >= 1, (
            sync_a.stats(), sync_b.stats(),
        )
        mean = jax.tree_util.tree_map(lambda t: t + 0.5, pa)
        got_a = srv_a.experts["rs.0"].state_dict()["params"]
        got_b = srv_b.experts["rs.0"].state_dict()["params"]
        for m, a, b in zip(
            jax.tree_util.tree_leaves(mean),
            jax.tree_util.tree_leaves(got_a),
            jax.tree_util.tree_leaves(got_b),
        ):
            # members end bitwise-equal per partition (PR 3 contract);
            # vs the analytic mean allow float tolerance
            np.testing.assert_array_equal(a, b)
            np.testing.assert_allclose(a, m, atol=1e-5)
    finally:
        for srv in (srv_a, srv_b):
            if srv is not None:
                srv.shutdown()
        reset_client_rpc()
        for d in (d_a, d_b, boot):
            d.shutdown()


def test_sole_endpoint_rescue_fresh_lookup_retry():
    """A NON-replicated uid whose only endpoint dies mid-record-TTL
    (ISSUE 11): no hedge backup exists, so the dispatch must do ONE
    cache-bypassing alive refresh, re-resolve the uid (simulating a
    migrated host that re-declared within a heartbeat), and retry the
    same prepared payload at the fresh endpoint — zero dropped samples,
    bitwise-identical reply (both servers crc32-seed ``hdg.0``)."""
    ctx_a, ctx_b = _replica_pair()
    with ctx_a as (ep_a, srv_a), ctx_b as (ep_b, _):
        source = StaticExpertSource({"hdg.0": ep_a})  # sole endpoint
        # short forward_timeout: the half-open connection to the killed
        # server HANGS (no RST) and only fails at the rpc timeout — the
        # rescue triggers on that failure, not on a magic fast error
        moe = RemoteMixtureOfExperts(
            in_features=HID, grid_size=(1,), uid_prefix="hdg",
            source=source, k_best=1, k_min=1, forward_timeout=2.0,
        )
        gate = moe.init_gate_params(jax.random.PRNGKey(0))
        y0 = np.asarray(moe(_x(), gate))
        srv_a.shutdown()
        source.experts["hdg.0"] = ep_b  # the 'migrated' re-declaration
        y1 = np.asarray(moe(_x(), gate))
        np.testing.assert_allclose(y1, y0, atol=1e-5)
        routing = moe.dispatch_stats()["routing"]
        assert routing["fresh_retries"] >= 1, routing
        assert routing["fresh_retry_wins"] >= 1, routing
        assert moe.samples_dropped == 0


def test_sole_endpoint_rescue_no_replacement_degrades():
    """The rescue fires at most once per uid and, when the fresh lookup
    finds no replacement (the static table still points at the corpse),
    the sample degrades through the normal quorum path instead of
    retrying forever."""
    ctx_a = background_server(
        hidden_dim=HID, expert_uids=["hdg.0"], optimizer=optax.sgd(0.0)
    )
    ctx_b = background_server(
        hidden_dim=HID, expert_uids=["hdg.1"], optimizer=optax.sgd(0.0)
    )
    with ctx_a as (ep_a, srv_a), ctx_b as (ep_b, _):
        source = StaticExpertSource({"hdg.0": ep_a, "hdg.1": ep_b})
        # grace (timeout_after_k_min) must outlive forward_timeout here:
        # hdg.1's fast reply meets the quorum and arms the grace period,
        # and the hung call to the corpse only fails at forward_timeout —
        # the rescue needs to fire inside that window to be observable
        moe = RemoteMixtureOfExperts(
            in_features=HID, grid_size=(2,), uid_prefix="hdg",
            source=source, k_best=2, k_min=1, forward_timeout=2.0,
            timeout_after_k_min=5.0,
        )
        gate = moe.init_gate_params(jax.random.PRNGKey(0))
        jax.block_until_ready(moe(_x(), gate))
        srv_a.shutdown()
        jax.block_until_ready(moe(_x(), gate))  # hdg.1 alone meets k_min=1
        routing = moe.dispatch_stats()["routing"]
        assert routing["fresh_retries"] >= 1, routing
        assert routing["fresh_retry_wins"] == 0, routing
        assert moe.samples_dropped == 0
