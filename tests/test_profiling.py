"""Profiling spans: disabled by default, capture RPC/dispatch timings when on."""

import numpy as np
import pytest

from learning_at_home_tpu.client import RemoteExpert, reset_client_rpc
from learning_at_home_tpu.server.server import background_server
from learning_at_home_tpu.utils.profiling import Timeline, timeline


def test_timeline_env_default(monkeypatch):
    monkeypatch.delenv("LAH_PROFILE", raising=False)
    assert not Timeline().enabled
    monkeypatch.setenv("LAH_PROFILE", "1")
    assert Timeline().enabled
    monkeypatch.setenv("LAH_PROFILE", "0")
    assert not Timeline().enabled


def test_timeline_basic():
    tl = Timeline()
    tl.enable()
    with tl.span("work"):
        pass
    tl.record("manual", 0.0, 0.25)
    summary = tl.summary()
    assert "work" in summary and "manual" in summary
    assert summary["manual"]["p50_ms"] == 250.0
    tl.clear()
    assert tl.summary() == {}


def test_spans_capture_rpc_path():
    timeline.enable()
    timeline.clear()
    try:
        with background_server(num_experts=1, hidden_dim=16, seed=0) as (ep, srv):
            expert = RemoteExpert("expert.0", ep)
            x = np.zeros((2, 16), np.float32)
            expert.forward_blocking([x])
            expert.forward_blocking([x])
        summary = timeline.summary()
        assert summary["rpc.forward"]["count"] == 2
        assert any(name.startswith("runtime.expert.0") for name in summary)
    finally:
        timeline.disable()
        timeline.clear()
        reset_client_rpc()


def test_disabled_timeline_records_nothing():
    tl = Timeline()
    tl.disable()
    with tl.span("x"):
        pass
    tl.record("y", 0, 1)
    assert tl.summary() == {}


def test_device_trace_captures(tmp_path):
    import os

    import jax
    import jax.numpy as jnp

    from learning_at_home_tpu.utils.profiling import device_trace

    with device_trace(str(tmp_path / "trace")):
        jax.jit(lambda x: (x @ x).sum())(jnp.ones((64, 64))).block_until_ready()
    # a jax.profiler trace directory with at least one artifact appeared
    found = [
        os.path.join(root, f)
        for root, _, files in os.walk(tmp_path / "trace")
        for f in files
    ]
    assert found, "device_trace produced no trace artifacts"
