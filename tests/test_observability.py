"""End-to-end observability (ISSUE 4): cross-peer trace propagation over
both wire protocols, Chrome trace export with correct nesting, the
per-peer metrics endpoint, and the DHT-discovered lah_top swarm view."""

import json
import os
import subprocess
import sys
import time
import urllib.request

import jax
import numpy as np
import optax
import pytest

from learning_at_home_tpu.client import RemoteExpert, reset_client_rpc
from learning_at_home_tpu.client.moe import RemoteMixtureOfExperts
from learning_at_home_tpu.client.routing import StaticExpertSource
from learning_at_home_tpu.client.rpc import (
    client_loop,
    pool_registry,
    set_dispatch_mode,
)
from learning_at_home_tpu.server import background_server
from learning_at_home_tpu.utils import connection as conn_mod
from learning_at_home_tpu.utils.profiling import timeline

HID = 16
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def profiled():
    """Timeline on + clean, pipelined mode, pools reset afterwards."""
    set_dispatch_mode("pipelined")
    timeline.enable()
    timeline.clear()
    yield timeline
    timeline.disable()
    timeline.clear()
    set_dispatch_mode("pipelined")
    reset_client_rpc()


def _make_moe(srv, endpoint, **kw):
    source = StaticExpertSource({uid: endpoint for uid in srv.experts})
    kw.setdefault("k_best", 2)
    kw.setdefault("k_min", 1)
    return RemoteMixtureOfExperts(
        in_features=HID, grid_size=(2,), uid_prefix="ffn", source=source,
        **kw,
    )


def _fwd_bwd(moe):
    gate = moe.init_gate_params(jax.random.PRNGKey(0))
    x = np.random.RandomState(0).randn(4, HID).astype(np.float32)

    def loss(g, x):
        return jax.numpy.sum(moe(x, g) ** 2)

    jax.grad(loss)(gate, jax.numpy.asarray(x))


def _traces_by_name(spans):
    """{span_name: set(trace ids)} over spans that carry one."""
    out = {}
    for name, _, _, trace, _ in spans:
        if trace is not None:
            out.setdefault(name, set()).add(trace)
    return out


def _interval(spans, name, trace):
    """(start, end) of the one span with this name+trace."""
    match = [
        (s, s + d) for n, s, d, t, _ in spans if n == name and t == trace
    ]
    assert match, f"no span {name!r} with trace {trace}"
    return match[0]


# ---------------------------------------------------------------------------
# trace propagation
# ---------------------------------------------------------------------------


def test_trace_joins_client_and_server_spans_v2_merged(profiled):
    """One traced dispatch over the merged-multi v2 path: client pack,
    rpc, server request, and the server's stack/dispatch/materialize
    spans all share ONE trace id — and nest on the time axis."""
    with background_server(
        num_experts=2, hidden_dim=HID, expert_prefix="ffn", seed=0
    ) as (endpoint, srv):
        moe = _make_moe(srv, endpoint)
        _fwd_bwd(moe)
    spans = timeline.spans()
    by_name = _traces_by_name(spans)
    # the dispatch umbrella carries exactly one trace id per dispatch
    assert "moe.dispatch.ffn" in by_name
    (trace,) = by_name["moe.dispatch.ffn"]
    for name in (
        "client.pack.forward",
        "rpc.multi",
        "server.request.multi",
        "runtime.stack.ffn.0.forward",
        "runtime.dispatch.ffn.0.forward",
        "runtime.materialize.ffn.0.forward",
        # backward joins the SAME trace (the session carries it)
        "moe.backward.ffn",
        "client.pack.backward",
        "runtime.dispatch.ffn.0.backward",
    ):
        assert trace in by_name.get(name, set()), (
            f"{name} not stamped with the dispatch trace; got {by_name}"
        )
    # nesting: server stage spans inside the server request span, which
    # sits inside the client's rpc span (same process, one clock)
    rpc_s, rpc_e = _interval(spans, "rpc.multi", trace)
    req_s, req_e = _interval(spans, "server.request.multi", trace)
    assert rpc_s <= req_s and req_e <= rpc_e
    for stage in ("stack", "dispatch", "materialize"):
        s, e = _interval(spans, f"runtime.{stage}.ffn.0.forward", trace)
        assert req_s <= s and e <= req_e, f"runtime.{stage} escapes request"


def test_trace_v1_fallback_roundtrip(profiled):
    """Legacy mode (protocol v1, serialize-on-loop): the trace id rides
    the same meta and still stamps server-side spans."""
    set_dispatch_mode("legacy")
    with background_server(
        num_experts=2, hidden_dim=HID, expert_prefix="ffn", seed=0
    ) as (endpoint, srv):
        moe = _make_moe(srv, endpoint)
        _fwd_bwd(moe)
    by_name = _traces_by_name(timeline.spans())
    (trace,) = by_name["moe.dispatch.ffn"]
    assert trace in by_name.get("rpc.multi", set())
    assert trace in by_name.get("server.request.multi", set())
    assert trace in by_name.get("runtime.dispatch.ffn.0.forward", set())
    # legacy mode has no host-thread pack stage, by design
    assert "client.pack.forward" not in by_name


def test_trace_survives_disaggregated_retry(profiled, monkeypatch):
    """A failed merged call disaggregates into per-expert singles — each
    retry carries the ORIGINAL dispatch's trace id."""
    real = conn_mod.ConnectionPool.rpc_prepared
    failed = {"n": 0}

    async def flaky(self, msg_type, wire, meta=None, timeout=None):
        if msg_type == "multi" and failed["n"] == 0:
            failed["n"] += 1
            raise ConnectionError("injected merged-call failure")
        return await real(self, msg_type, wire, meta, timeout)

    monkeypatch.setattr(conn_mod.ConnectionPool, "rpc_prepared", flaky)
    with background_server(
        num_experts=2, hidden_dim=HID, expert_prefix="ffn", seed=0
    ) as (endpoint, srv):
        moe = _make_moe(srv, endpoint)
        gate = moe.init_gate_params(jax.random.PRNGKey(0))
        x = jax.numpy.asarray(
            np.random.RandomState(0).randn(4, HID).astype(np.float32)
        )
        y = moe(x, gate)
        assert np.isfinite(np.asarray(y)).all()
    assert failed["n"] == 1, "the merged call was never failed"
    by_name = _traces_by_name(timeline.spans())
    (trace,) = by_name["moe.dispatch.ffn"]
    # the disaggregated singles went out as rpc.forward with the trace
    assert trace in by_name.get("rpc.forward", set())
    assert trace in by_name.get("server.request.forward", set())


def test_trace_echoed_in_reply_meta_and_always_on_stats(profiled):
    """The reply meta echoes a valid request trace; a malformed
    (non-string) trace is dropped, never trusted.  The same exchange
    shows the stats RPC's always-on registry section (satellite: a
    server is never blind without LAH_PROFILE)."""
    timeline.disable()  # always-on means: works with profiling OFF
    with background_server(
        num_experts=1, hidden_dim=HID, expert_prefix="ffn", seed=0
    ) as (endpoint, _srv):

        async def call(meta):
            pool = pool_registry().get(endpoint)
            return await pool.rpc("stats", (), meta, timeout=15)

        _, meta = client_loop().run(call({"trace": "ab" * 8}))
        assert meta["trace"] == "ab" * 8
        assert "metrics" in meta and "collected" in meta["metrics"]
        headline = meta["metrics"]["collected"]
        assert "lah_server_jobs_processed_total" in headline
        # span summaries are OPT-IN (O(spans) work on the serving loop);
        # the default stats reply omits them entirely
        assert "spans" not in meta
        _, meta_s = client_loop().run(
            call({"trace": "ab" * 8, "spans": True})
        )
        assert meta_s["spans"] == {}  # profiling off → present but empty
        _, meta2 = client_loop().run(call({"trace": 12345}))
        assert "trace" not in meta2


def test_merged_multi_trainer_batch_is_unstamped():
    """A batch that merged tasks from TWO different traces has no single
    owner: the runtime stage spans stay trace-free instead of
    misattributing shared work to one request."""
    from learning_at_home_tpu.server.runtime import _job_trace
    from learning_at_home_tpu.server.task_pool import BatchJob, TaskPool

    def job(traces):
        return BatchJob(
            priority=0.0, seq=0, pool=TaskPool(lambda i: i, "p"),
            task_tensors=[], row_spans=[], n_rows=0, traces=traces,
        )

    assert _job_trace(job(["aa", "aa"])) == "aa"
    assert _job_trace(job(["aa", None])) == "aa"
    assert _job_trace(job(["aa", "bb"])) is None
    assert _job_trace(job([None])) is None
    assert _job_trace(job([])) is None


# ---------------------------------------------------------------------------
# the per-peer metrics endpoint
# ---------------------------------------------------------------------------


def _get(port, path):
    return urllib.request.urlopen(
        f"http://127.0.0.1:{port}{path}", timeout=10
    )


def test_metrics_endpoint_routes(profiled):
    with background_server(
        num_experts=1, hidden_dim=HID, expert_prefix="ffn", seed=0
    ) as (endpoint, srv):
        expert = RemoteExpert("ffn.0", endpoint, timeout=30.0)
        expert.forward_blocking([np.ones((2, HID), np.float32)])
        assert srv.metrics_port
        text = _get(srv.metrics_port, "/metrics").read().decode()
        assert "lah_server_jobs_processed_total" in text
        assert "lah_server_updates_total" in text
        doc = json.loads(_get(srv.metrics_port, "/metrics.json").read())
        assert doc["meta"]["role"] == "server"
        assert doc["experts"] == {"ffn.0": 0}
        assert doc["runtime"]["jobs_processed"] >= 1
        assert (
            doc["metrics"]["collected"]["lah_server_jobs_processed_total"]
            >= 1
        )
        trace_doc = json.loads(_get(srv.metrics_port, "/trace").read())
        assert any(
            ev.get("name", "").startswith("runtime.")
            for ev in trace_doc["traceEvents"]
        )
        assert _get(srv.metrics_port, "/healthz").read() == b"ok"
        with pytest.raises(urllib.error.HTTPError):
            _get(srv.metrics_port, "/nope")


# ---------------------------------------------------------------------------
# the acceptance smoke: 2 servers + 1 trainer, one joined chrome trace,
# lah_top aggregation via DHT discovery (no endpoint on the CLI)
# ---------------------------------------------------------------------------


def test_two_server_trainer_smoke_chrome_trace_and_lah_top(
    profiled, tmp_path
):
    from learning_at_home_tpu.dht import DHT
    from learning_at_home_tpu.server.server import Server
    from learning_at_home_tpu.utils.telemetry import (
        TelemetryPublisher,
        discover_telemetry,
    )

    bootstrap = DHT()
    dht = DHT(initial_peers=[bootstrap.endpoint])
    servers, telemetry = [], None
    try:
        for i in range(2):
            servers.append(
                Server.create(
                    num_experts=1, expert_cls="ffn", hidden_dim=HID,
                    expert_prefix="ffn", expert_offset=i,
                    optimizer=optax.sgd(0.05), max_batch_size=64,
                    host="127.0.0.1", dht=dht, update_period=2.0,
                )
            )
        # the trainer: drives one traced fwd+bwd through the DHT-routed
        # MoE and advertises its own metrics endpoint
        telemetry = TelemetryPublisher(
            dht, role="trainer", period=2.0
        ).start()
        moe = RemoteMixtureOfExperts(
            in_features=HID, grid_size=(2,), uid_prefix="ffn", source=dht,
            k_best=2, k_min=1,
        )
        deadline = time.time() + 30
        while time.time() < deadline:
            alive = client_loop().run(
                moe.alive_cache.get(force_refresh=True)
            )
            if len(alive) >= 2:
                break
            time.sleep(0.5)
        assert len(alive) >= 2, f"experts never appeared via DHT: {alive}"
        _fwd_bwd(moe)

        # (a) ONE exported Chrome trace: a single dispatch's client pack
        # / rpc / server stack / dispatch / materialize spans share one
        # trace id and nest correctly
        spans = timeline.spans()
        (trace,) = _traces_by_name(spans)["moe.dispatch.ffn"]
        path = tmp_path / "swarm_trace.json"
        timeline.save_chrome_trace(str(path), process_name="smoke")
        events = json.loads(path.read_text())["traceEvents"]
        traced = [
            e for e in events
            if e.get("ph") == "X" and e.get("args", {}).get("trace") == trace
        ]
        names = {e["name"] for e in traced}
        assert any(n.startswith("client.pack.forward") for n in names)
        assert any(n.startswith("rpc.") for n in names)
        for stage in ("stack", "dispatch", "materialize"):
            assert any(
                n.startswith(f"runtime.{stage}.ffn.") for n in names
            ), f"no {stage} span in the exported trace: {names}"
        # nesting in the EXPORTED events (µs timeline)
        reqs = [e for e in traced if e["name"].startswith("server.request.")]
        stages = [e for e in traced if e["name"].startswith("runtime.")
                  and e["name"].count(".") > 2]
        assert reqs and stages
        for st in stages:
            assert any(
                r["ts"] <= st["ts"]
                and st["ts"] + st["dur"] <= r["ts"] + r["dur"]
                for r in reqs
            ), f"{st['name']} nests in no server.request span"

        # (b) lah_top --once aggregates BOTH servers' live metrics,
        # discovered via the DHT — only the DHT bootstrap is on the CLI
        deadline = time.time() + 30
        while time.time() < deadline:
            peers = discover_telemetry(bootstrap)
            if sum(1 for p in peers.values() if p["role"] == "server") >= 2:
                break
            time.sleep(0.5)
        assert sum(1 for p in peers.values() if p["role"] == "server") >= 2, peers
        r = subprocess.run(
            [
                sys.executable, os.path.join(REPO, "tools", "lah_top.py"),
                "--once", "--initial-peers",
                f"{bootstrap.endpoint[0]}:{bootstrap.endpoint[1]}",
                "--dump-trace", str(tmp_path / "fetched_trace.json"),
            ],
            capture_output=True, text=True, timeout=120, cwd=REPO,
        )
        assert r.returncode == 0, f"{r.stdout}\n{r.stderr}"
        for srv in servers:
            assert f"server-127.0.0.1:{srv.port}" in r.stdout, r.stdout
        assert "trainer-" in r.stdout, r.stdout
        assert "ffn.0" in r.stdout and "ffn.1" in r.stdout, r.stdout
        # the merged /trace dump is valid chrome trace JSON too
        fetched = json.loads((tmp_path / "fetched_trace.json").read_text())
        assert isinstance(fetched["traceEvents"], list)
    finally:
        if telemetry is not None:
            telemetry.stop()
        for srv in servers:
            srv.shutdown()
        dht.shutdown()
        bootstrap.shutdown()
