"""Latency-aware routing cost model + replica-set plumbing (ISSUE 8).

Pure/fast units: RoutingCostModel's predicted-completion-time scoring
(and its bias=0 ⇒ bitwise-blind-gate A/B contract), alive-map replica
normalization, the replica-aware DHT subkey scheme, the load/wanted
telemetry record parsers, and the rebalancer's pure planning step.
Hedged DISPATCH behavior (real servers) lives in test_replication.py.
"""

import time

import numpy as np
import pytest

from learning_at_home_tpu.client.routing import (
    DEFAULT_COST_WEIGHT,
    RoutingCostModel,
    as_replica_set,
    endpoint_key,
    make_uid,
    select_top_k,
)
from learning_at_home_tpu.utils.telemetry import (
    load_key,
    parse_load_value,
    parse_wanted_value,
    replicas_wanted_key,
)

EP_A = ("10.0.0.1", 9000)
EP_B = ("10.0.0.2", 9000)
EP_C = ("10.0.0.3", 9000)


class FakePool:
    def __init__(self, rtt_ema=None, bw_ema=None):
        self.rtt_ema = rtt_ema
        self.bw_ema = bw_ema


class FakeRegistry:
    """RoutingCostModel only needs ``peek`` (non-creating lookup)."""

    def __init__(self, pools: dict):
        self.pools = pools

    def peek(self, endpoint):
        return self.pools.get(endpoint)


# ---- as_replica_set normalization ----


def test_as_replica_set_bare_endpoint_is_singleton():
    assert as_replica_set(("10.0.0.1", 9000)) == (("10.0.0.1", 9000),)
    # list form and numeric-string port both normalize
    assert as_replica_set(["10.0.0.1", "9000"]) == (("10.0.0.1", 9000),)


def test_as_replica_set_preserves_order_and_dedupes():
    got = as_replica_set((EP_B, EP_A, EP_B, ("10.0.0.1", "9000")))
    assert got == (EP_B, EP_A)


def test_as_replica_set_drops_malformed_entries():
    # peer-supplied alive maps: junk inside a set is dropped, not raised
    got = as_replica_set([EP_A, None, ("host",), (1234, 9000), "xx", EP_B])
    assert got == (EP_A, EP_B)
    assert as_replica_set([]) == ()


# ---- the A/B contract: weight 0 ⇒ bias None ⇒ bitwise blind gate ----


def test_zero_weight_bias_is_none_even_with_signal():
    reg = FakeRegistry({EP_A: FakePool(rtt_ema=0.5)})
    model = RoutingCostModel(0.0, registry=reg)
    bias = model.bias(["u.0"], {"u.0": (EP_A,)})
    assert bias is None
    assert model.bias_applied == 0


def test_select_top_k_bias_none_is_bitwise_identical():
    """``bias=None`` must reproduce the no-bias call EXACTLY — the
    acceptance criterion's bias=0 arm is today's selection bitwise."""
    rs = np.random.RandomState(0)
    logits = [rs.randn(16, 8).astype(np.float32)]
    uids = [make_uid("p", (i,)) for i in range(8)]
    sel0, coords0 = select_top_k(logits, uids, k=3)
    sel1, coords1 = select_top_k(logits, uids, k=3, bias=None)
    np.testing.assert_array_equal(sel0, sel1)
    np.testing.assert_array_equal(coords0, coords1)


def test_no_signal_anywhere_bias_is_none():
    """Unmeasured swarm (no pool EMA, no load record): bias None — the
    gate stays exactly blind rather than biased by a zeros vector."""
    model = RoutingCostModel(DEFAULT_COST_WEIGHT, registry=FakeRegistry({}))
    bias = model.bias(["u.0", "u.1"], {"u.0": (EP_A,), "u.1": (EP_B,)})
    assert bias is None
    assert model.bias_applied == 0


# ---- predicted completion time ----


def test_predicted_cost_sums_rtt_queue_and_transfer():
    reg = FakeRegistry({EP_A: FakePool(rtt_ema=0.1, bw_ema=1e6)})
    model = RoutingCostModel(
        1.0, registry=reg, queue_cost_s=0.01, codec_ratio=0.5,
        load_getter=lambda: {endpoint_key(EP_A): {"q": 4.0, "n": 2, "hot": {}}},
    )
    # rtt 0.1 + 4 queued × 0.01 + 1e6 B × 0.5 / 1e6 B/s = 0.1+0.04+0.5
    cost = model.predicted_cost_s(EP_A, nbytes=1_000_000)
    assert cost == pytest.approx(0.64, abs=1e-9)
    # no bytes → no transfer term; unknown endpoint → None (no signal)
    assert model.predicted_cost_s(EP_A) == pytest.approx(0.14, abs=1e-9)
    assert model.predicted_cost_s(EP_B) is None


def test_rtt_only_cost_reproduces_legacy_latency_weight_bias():
    """With no load feed and no bandwidth measurement the model IS the
    historical ``latency_weight`` bias: -weight × rtt_ema per uid."""
    reg = FakeRegistry({EP_A: FakePool(rtt_ema=0.25), EP_B: FakePool()})
    w = 20.0
    model = RoutingCostModel(w, registry=reg)
    uids = ["l.0", "l.1"]
    bias = model.bias(uids, {"l.0": (EP_A,), "l.1": (EP_B,)})
    legacy = np.zeros(2, np.float32)
    legacy[0] = -w * 0.25  # exactly the pre-ISSUE-8 computation
    np.testing.assert_array_equal(bias, legacy)
    assert model.bias_applied == 1


def test_bias_takes_min_cost_over_replica_set():
    """A uid's cost is its CHEAPEST replica — the dispatch will pick it."""
    reg = FakeRegistry(
        {EP_A: FakePool(rtt_ema=0.5), EP_B: FakePool(rtt_ema=0.05)}
    )
    model = RoutingCostModel(10.0, registry=reg)
    bias = model.bias(["r.0"], {"r.0": (EP_A, EP_B)})
    assert bias[0] == pytest.approx(-10.0 * 0.05)


def test_order_replicas_cheapest_first_deterministic():
    reg = FakeRegistry(
        {EP_A: FakePool(rtt_ema=0.5), EP_B: FakePool(rtt_ema=0.05)}
    )
    model = RoutingCostModel(0.0, registry=reg)  # ordering works at weight 0
    assert model.order_replicas((EP_A, EP_B)) == (EP_B, EP_A)
    # unmeasured replicas cost 0 (optimistic exploration) and exact ties
    # break on the endpoint itself — stable across shuffles
    assert model.order_replicas((EP_C, EP_B, EP_A)) == (EP_C, EP_B, EP_A)
    assert model.order_replicas((EP_B, EP_C, EP_A)) == (EP_C, EP_B, EP_A)


# ---- load feed: TTL refresh discipline ----


def test_load_refresh_once_per_ttl_window_and_failure_keeps_stale():
    calls = []

    def getter():
        calls.append(time.monotonic())
        if len(calls) == 2:
            raise OSError("dht flake")
        return {endpoint_key(EP_A): {"q": float(len(calls)), "n": 1, "hot": {}}}

    model = RoutingCostModel(
        1.0, registry=FakeRegistry({}), load_getter=getter, load_ttl=0.05
    )
    assert model.queue_depth(EP_A) == 1.0
    assert model.queue_depth(EP_A) == 1.0  # within TTL: no second call
    assert len(calls) == 1
    time.sleep(0.06)
    # refresh fails → stale map survives one window, failure is counted
    assert model.queue_depth(EP_A) == 1.0
    assert len(calls) == 2
    assert model.load_refresh_failures == 1
    time.sleep(0.06)
    assert model.queue_depth(EP_A) == 3.0
    assert len(calls) == 3


# ---- telemetry record parsers (peer-supplied → never raise) ----


def test_parse_load_value():
    rec = parse_load_value({"q": "3", "n": 2, "hot": {"u.0": 9.5, "u.1": "x"}})
    assert rec == {"q": 3.0, "n": 2, "hot": {"u.0": 9.5}}
    assert parse_load_value(["not", "a", "dict"]) is None
    assert parse_load_value({"q": "NaN?", "n": {}}) is None
    assert parse_load_value({})["q"] == 0.0


def test_parse_wanted_value():
    assert parse_wanted_value([9.5, "10.0.0.1", 9000]) == {
        "depth": 9.5, "endpoint": ("10.0.0.1", 9000)
    }
    for junk in (None, [], ["x"], [1.0, 2, 3], [1.0, "h", "port"]):
        assert parse_wanted_value(junk) is None


def test_key_families_scoped_by_prefix():
    assert load_key("swarm") == "load.swarm"
    assert replicas_wanted_key("swarm") == "replicas.wanted.swarm"


# ---- replica-aware DHT scheme ----


def test_dht_replica_aware_declare_and_resolution():
    """Two servers declaring ONE uid coexist as subkey records: readers
    aggregate a replica set, single-endpoint resolution stays
    deterministic, and a legacy bare-uid prefix record still reads as a
    single-replica entry (mixed-build swarm)."""
    from learning_at_home_tpu.dht import DHT

    d1 = DHT()
    d2 = DHT(initial_peers=[d1.endpoint])
    reader = DHT(initial_peers=[d1.endpoint])
    try:
        d1.declare_experts_sync(["rep.0", "rep.1"], EP_A, expiration=30)
        d2.declare_experts_sync(["rep.0"], EP_B, expiration=30)
        alive = reader._loop.run(reader._get_alive("rep"))
        # replicated uid → tuple (sorted); single hoster → bare endpoint
        assert as_replica_set(alive["rep.0"]) == (EP_A, EP_B)
        assert alive["rep.1"] == EP_A
        # full-uid resolution picks ONE deterministic replica
        eps = reader.get_experts_sync(["rep.0", "rep.1"])
        assert eps["rep.0"] in (EP_A, EP_B)
        assert eps["rep.1"] == EP_A
        # legacy prefix entry (old build: subkey = bare uid) still counts
        reader.store_sync("rep", [EP_C[0], EP_C[1]], 30, subkey="rep.2")
        alive = reader._loop.run(reader._get_alive("rep"))
        assert alive["rep.2"] == EP_C
    finally:
        reader.shutdown()
        d2.shutdown()
        d1.shutdown()


# ---- swarm link prior (ISSUE 16 placement/routing co-optimization) ----


def test_link_prior_used_when_no_local_measurement():
    """Never-dialed endpoint + published link record → the prediction
    falls back to the swarm prior (rtt AND bandwidth) and counts it."""
    model = RoutingCostModel(
        1.0, registry=FakeRegistry({}), codec_ratio=1.0,
        link_getter=lambda: {
            endpoint_key(EP_A): {"rtt_s": 0.2, "bw_bps": 1e6},
        },
    )
    cost = model.predicted_cost_s(EP_A, nbytes=500_000)
    assert cost == pytest.approx(0.2 + 0.5, abs=1e-9)
    assert model.link_fallbacks == 1
    # no prior for EP_B either → still no signal
    assert model.predicted_cost_s(EP_B) is None


def test_local_pool_measurement_beats_link_prior():
    reg = FakeRegistry({EP_A: FakePool(rtt_ema=0.05)})
    model = RoutingCostModel(
        1.0, registry=reg,
        link_getter=lambda: {endpoint_key(EP_A): {"rtt_s": 0.9, "bw_bps": None}},
    )
    assert model.predicted_cost_s(EP_A) == pytest.approx(0.05)
    assert model.link_fallbacks == 0


def test_no_link_getter_is_bitwise_pre_change():
    """link_getter=None (the default): the prior map stays empty and an
    unmeasured endpoint still predicts None — exactly the pre-ISSUE-16
    model."""
    model = RoutingCostModel(1.0, registry=FakeRegistry({}))
    assert model.links() == {}
    assert model.predicted_cost_s(EP_A) is None
    assert model.link_fallbacks == 0


def test_link_refresh_ttl_and_failure_keeps_stale():
    calls = []

    def getter():
        calls.append(1)
        if len(calls) == 2:
            raise OSError("dht flake")
        return {endpoint_key(EP_A): {"rtt_s": 0.1 * len(calls), "bw_bps": None}}

    model = RoutingCostModel(
        1.0, registry=FakeRegistry({}), link_getter=getter, link_ttl=0.05
    )
    assert model.predicted_cost_s(EP_A) == pytest.approx(0.1)
    assert model.predicted_cost_s(EP_A) == pytest.approx(0.1)
    assert len(calls) == 1  # within TTL: no second fetch
    time.sleep(0.06)
    # refresh fails → stale prior survives one window, failure counted
    assert model.predicted_cost_s(EP_A) == pytest.approx(0.1)
    assert model.link_refresh_failures == 1
    time.sleep(0.06)
    assert model.predicted_cost_s(EP_A) == pytest.approx(0.3)
    assert model.link_fallbacks == 4


def test_link_prior_garbage_record_is_no_signal():
    model = RoutingCostModel(
        1.0, registry=FakeRegistry({}),
        link_getter=lambda: {
            endpoint_key(EP_A): {"rtt_s": "soon", "bw_bps": 1e6},
            endpoint_key(EP_B): "junk",
        },
    )
    assert model.predicted_cost_s(EP_A) is None
    assert model.predicted_cost_s(EP_B) is None
    assert model.link_fallbacks == 0


# ---- rebalancer planning (tools/lah_rebalance.py, pure step) ----


def _plan(wanted, loads, hosters, max_replicas=2):
    import importlib.util
    import os

    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tools", "lah_rebalance.py",
    )
    spec = importlib.util.spec_from_file_location("lah_rebalance", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod.plan_actions(wanted, loads, hosters, max_replicas)


def test_plan_actions_targets_least_loaded_hottest_first():
    wanted = {
        "h.0": {"depth": 12.0, "endpoint": EP_A},
        "h.1": {"depth": 30.0, "endpoint": EP_A},
    }
    loads = {
        endpoint_key(EP_A): {"q": 9.0, "n": 4},
        endpoint_key(EP_B): {"q": 0.0, "n": 1},
        endpoint_key(EP_C): {"q": 0.0, "n": 1},
    }
    hosters = {"h.0": {EP_A}, "h.1": {EP_A}}
    actions = _plan(wanted, loads, hosters)
    assert [a["uid"] for a in actions] == ["h.1", "h.0"]
    # hottest goes to the least-loaded box (endpoint tie-break: B < C);
    # the pass then spreads — B's planned count is bumped, so the next
    # uid prefers the still-cold C instead of dog-piling B
    assert actions[0]["target"] == EP_B
    assert actions[1]["target"] == EP_C


def test_plan_actions_respects_max_replicas_and_existing_hosters():
    wanted = {"h.0": {"depth": 12.0, "endpoint": EP_A}}
    loads = {
        endpoint_key(EP_A): {"q": 0.0, "n": 1},
        endpoint_key(EP_B): {"q": 0.0, "n": 1},
    }
    # already at 2 hosters → no action at max_replicas=2
    assert _plan(wanted, loads, {"h.0": {EP_A, EP_B}}) == []
    # the only candidate already hosts it → no action (never dog-pile)
    assert _plan(wanted, {endpoint_key(EP_A): {"q": 0.0, "n": 1}},
                 {"h.0": {EP_A}}) == []
