"""Elastic swarm lifecycle tests (ISSUE 9): graceful drain, live expert
migration (bitwise params + optimizer state), checkpoint fallback,
restart-from-checkpoint rejoin, and the zero-disruption drain contract."""

import time

import jax
import numpy as np
import optax
import pytest

from learning_at_home_tpu.client import reset_client_rpc
from learning_at_home_tpu.dht import DHT
from learning_at_home_tpu.server import lifecycle
from learning_at_home_tpu.server.server import Server


@pytest.fixture(autouse=True)
def _reset_rpc():
    yield
    reset_client_rpc()


def _state_leaves(state: dict) -> list:
    return [
        np.asarray(leaf)
        for leaf in jax.tree_util.tree_leaves(
            {"params": state["params"], "opt_state": state["opt_state"]}
        )
    ]


def assert_state_bitwise(a: dict, b: dict):
    la, lb = _state_leaves(a), _state_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        np.testing.assert_array_equal(x, y)


# ---------------------------------------------------------------------------
# pure wire helpers
# ---------------------------------------------------------------------------


def test_split_parts_respects_cap_and_keeps_order():
    leaves = [np.zeros(n, np.float32) for n in (10, 10, 1000, 10)]
    parts = lifecycle.split_parts(leaves, part_bytes=100)
    # order preserved, every index exactly once
    assert [i for part in parts for i in part] == [0, 1, 2, 3]
    # the 4000-byte leaf exceeds the cap: it travels alone
    assert [2] in parts
    for part in parts:
        if part != [2]:
            assert sum(leaves[i].nbytes for i in part) <= 100
    # zero leaves still yields one (empty) part: the RPC sequence exists
    assert lifecycle.split_parts([], part_bytes=100) == [[]]


def test_verify_manifest_catches_any_bit_flip():
    leaves, manifest = lifecycle.flatten_state(
        {"params": {"w": np.arange(8, dtype=np.float32)},
         "opt_state": {"c": np.ones((2, 3), np.int32)}}
    )
    assert lifecycle.verify_manifest(leaves, manifest)
    f32_idx = next(
        i for i, l in enumerate(leaves) if l.dtype == np.float32
    )
    flipped = list(leaves)
    flipped[f32_idx] = leaves[f32_idx].copy()
    flipped[f32_idx][3] = np.nextafter(
        flipped[f32_idx][3], np.float32(np.inf), dtype=np.float32
    )  # exactly one ULP
    assert not lifecycle.verify_manifest(flipped, manifest)
    # shape/dtype/count mismatches are refusals, not crashes
    assert not lifecycle.verify_manifest(leaves[:1], manifest)
    cast = list(leaves)
    cast[f32_idx] = leaves[f32_idx].astype(np.float64)
    assert not lifecycle.verify_manifest(cast, manifest)
    reshaped = list(leaves)
    reshaped[f32_idx] = leaves[f32_idx].reshape(2, 4)
    assert not lifecycle.verify_manifest(reshaped, manifest)


# ---------------------------------------------------------------------------
# drain state machine + heartbeat steering
# ---------------------------------------------------------------------------


def test_drain_flips_state_and_stops_expert_heartbeat():
    boot = DHT()
    d_a = DHT(initial_peers=[boot.endpoint])
    d_c = DHT(initial_peers=[boot.endpoint])
    srv = Server.create(
        expert_uids=["dr.0"], hidden_dim=8, host="127.0.0.1",
        optimizer=optax.sgd(0.01), dht=d_a, update_period=0.4,
    )
    try:
        deadline = time.time() + 20
        while time.time() < deadline:
            if d_c._loop.run(d_c._get_alive("dr")):
                break
            time.sleep(0.1)
        assert d_c._loop.run(d_c._get_alive("dr")), "never declared"
        assert srv.lifecycle_state == lifecycle.SERVING
        # no successor, no checkpoint root: the drain just steers away
        summary = srv.drain(grace=0.0, quiesce_timeout=2.0, handoff=False)
        assert srv.lifecycle_state == lifecycle.DRAINED
        assert summary["handed_off"] == []
        assert srv.wait_drained(timeout=1.0)
        # expert records expire (one TTL = 2 x update_period) because the
        # DRAINING/DRAINED server no longer re-declares them
        deadline = time.time() + 10
        while time.time() < deadline:
            if not d_c._loop.run(d_c._get_alive("dr")):
                break
            time.sleep(0.2)
        assert not d_c._loop.run(d_c._get_alive("dr")), (
            "expert records survived the drain"
        )
        # draining twice is an error, not a second drain
        with pytest.raises(RuntimeError):
            srv.drain(grace=0.0)
        info = srv.lifecycle_info()
        assert info["state"] == lifecycle.DRAINED
        assert info["restarts"] == 0
        assert info["uptime_s"] >= 0
    finally:
        srv.shutdown()
        for d in (d_a, d_c, boot):
            d.shutdown()


# ---------------------------------------------------------------------------
# live migration: bitwise params + optimizer state
# ---------------------------------------------------------------------------


def test_handoff_bitwise_params_and_opt_state():
    boot = DHT()
    d_a = DHT(initial_peers=[boot.endpoint])
    d_b = DHT(initial_peers=[boot.endpoint])
    d_c = DHT(initial_peers=[boot.endpoint])
    srv_a = Server.create(
        expert_uids=["mig.0", "mig.1"], hidden_dim=16, host="127.0.0.1",
        optimizer=optax.adam(1e-3), dht=d_a, update_period=0.5,
    )
    srv_b = Server.create(
        num_experts=0, hidden_dim=16, host="127.0.0.1",
        optimizer=optax.adam(1e-3), dht=d_b, update_period=0.5,
    )
    try:
        # async updates make opt_state non-trivial (adam moments + count)
        x = np.random.RandomState(0).randn(4, 16).astype(np.float32)
        g = np.ones((4, 16), np.float32)
        srv_a.experts["mig.0"].backward([x], [g])
        srv_a.experts["mig.0"].backward([x], [g])
        want = {uid: b.state_dict() for uid, b in srv_a.experts.items()}
        fwd_before = np.asarray(srv_a.experts["mig.0"].forward([x])[0])

        summary = srv_a.drain(
            successor=srv_b.endpoint, grace=0.0, quiesce_timeout=3.0
        )
        assert summary["handed_off"] == ["mig.0", "mig.1"]
        assert summary["failed"] == []
        # the drained server no longer hosts (or serves) the experts
        assert not srv_a.experts
        # MIGRATION CORRECTNESS (acceptance): params AND optimizer state
        # bitwise-equal on the successor, update_count carried
        for uid, state in want.items():
            got = srv_b.experts[uid].state_dict()
            assert_state_bitwise(state, got)
            assert got["update_count"] == state["update_count"]
        assert srv_b.migrated_in == {"mig.0", "mig.1"}
        assert srv_b.handoff.received == 2
        # the migrated expert SERVES the same function bitwise
        fwd_after = np.asarray(srv_b.experts["mig.0"].forward([x])[0])
        np.testing.assert_array_equal(fwd_before, fwd_after)
        # and the successor declared the uids (discoverable via DHT)
        deadline = time.time() + 10
        alive = {}
        while time.time() < deadline:
            alive = d_c._loop.run(d_c._get_alive("mig"))
            if "mig.0" in alive and "mig.1" in alive:
                break
            time.sleep(0.2)
        assert "mig.0" in alive and "mig.1" in alive
    finally:
        for srv in (srv_a, srv_b):
            srv.shutdown()
        for d in (d_a, d_b, d_c, boot):
            d.shutdown()


def test_handoff_overwrites_existing_replica_bitwise():
    """A successor already hosting the uid (as a replica) receives the
    migrated — more-trained — state in place of its own copy."""
    srv_a = Server.create(
        expert_uids=["ow.0"], hidden_dim=8, host="127.0.0.1",
        optimizer=optax.sgd(0.05), dht=None,
    )
    srv_b = Server.create(
        expert_uids=["ow.0"], hidden_dim=8, host="127.0.0.1",
        optimizer=optax.sgd(0.05), dht=None,
    )
    try:
        x = np.random.RandomState(1).randn(2, 8).astype(np.float32)
        g = np.ones((2, 8), np.float32)
        srv_a.experts["ow.0"].backward([x], [g])  # A diverges from B
        want = srv_a.experts["ow.0"].state_dict()
        summary = srv_a.drain(
            successor=srv_b.endpoint, grace=0.0, quiesce_timeout=2.0
        )
        assert summary["handed_off"] == ["ow.0"]
        got = srv_b.experts["ow.0"].state_dict()
        assert_state_bitwise(want, got)
        assert got["update_count"] == 1
        # overwrite path: not re-registered as a replica, but counted in
        assert "ow.0" in srv_b.migrated_in
    finally:
        srv_a.shutdown()
        srv_b.shutdown()


def test_handoff_refused_without_recipe_falls_back_to_checkpoint(tmp_path):
    """A successor that cannot build the expert (no replica recipe)
    refuses the migration; the drain falls back to a checkpoint save the
    restarted server recovers from — bitwise."""
    from learning_at_home_tpu.utils.checkpoint import latest_step

    root = str(tmp_path / "fallback")
    srv_a = Server.create(
        expert_uids=["fb.0"], hidden_dim=8, host="127.0.0.1",
        optimizer=optax.adam(1e-3), dht=None,
    )
    srv_a.replica_checkpoint_root = root
    # a bare Server (no .create) has no recipe to rebuild experts from
    srv_b = Server({}, host="127.0.0.1", dht=None)
    srv_b.run_in_background()
    try:
        x = np.random.RandomState(2).randn(2, 8).astype(np.float32)
        srv_a.experts["fb.0"].backward([x], [np.ones((2, 8), np.float32)])
        want = srv_a.experts["fb.0"].state_dict()
        summary = srv_a.drain(
            successor=srv_b.endpoint, grace=0.0, quiesce_timeout=2.0
        )
        assert summary["handed_off"] == []
        assert summary["failed"] == ["fb.0"]
        assert summary["checkpointed"] == ["fb.0"]
        assert "fb.0" not in srv_b.experts
        assert srv_b.handoff.received == 0
        step = latest_step(root)
        assert step == summary["checkpoint_step"]
        # a restarted server recovers the checkpointed state bitwise
        srv_c = Server.create(
            expert_uids=["fb.0"], hidden_dim=8, host="127.0.0.1",
            optimizer=optax.adam(1e-3), dht=None, start=False,
        )
        srv_c.load_checkpoint(root)
        assert_state_bitwise(want, srv_c.experts["fb.0"].state_dict())
    finally:
        srv_a.shutdown()
        srv_b.shutdown()


def test_draining_server_refuses_inbound_handoff():
    """Drains must not chain: a draining successor refuses migrations
    (the sender picks another successor or checkpoints)."""
    from learning_at_home_tpu.server.lifecycle import (
        HandoffError,
        send_expert_handoff,
    )

    srv_a = Server.create(
        expert_uids=["ch.0"], hidden_dim=8, host="127.0.0.1",
        optimizer=optax.sgd(0.0), dht=None,
    )
    srv_b = Server.create(
        num_experts=0, hidden_dim=8, host="127.0.0.1",
        optimizer=optax.sgd(0.0), dht=None,
    )
    try:
        srv_b.drain(grace=0.0, quiesce_timeout=1.0, handoff=False)
        with pytest.raises(HandoffError, match="DRAINED"):
            send_expert_handoff(
                srv_b.endpoint, "ch.0",
                srv_a.experts["ch.0"].state_dict(), timeout=10.0,
            )
        assert "ch.0" not in srv_b.experts
    finally:
        srv_a.shutdown()
        srv_b.shutdown()


def test_handoff_hostile_meta_rejected():
    """Peer-supplied handoff meta is validated structurally: bad
    sessions, out-of-order parts, manifest mismatches and wire-coded
    payloads are error replies — never installs, never crashes."""
    from learning_at_home_tpu.client.rpc import client_loop, pool_registry
    from learning_at_home_tpu.utils.connection import RemoteCallError

    srv = Server.create(
        num_experts=0, hidden_dim=8, host="127.0.0.1",
        optimizer=optax.sgd(0.0), dht=None,
    )
    pool = pool_registry().get(srv.endpoint)

    def rpc(meta, tensors=()):
        return client_loop().run(
            pool.rpc("handoff", tensors, meta, timeout=10.0)
        )

    # the pinned ORDERED battery (tests/fuzz_corpus, ISSUE 15): the ok
    # entry opens session s3 that later entries kill and re-probe
    import json
    import os

    path = os.path.join(os.path.dirname(__file__), "fuzz_corpus",
                        "handoff_meta.json")
    with open(path) as fh:
        corpus = json.load(fh)
    assert corpus["format"] == "lah-fuzz-battery-v1"
    arr = np.ones(3, np.float32)
    manifest = [{"shape": [3], "dtype": "float32",
                 "crc": lifecycle._leaf_crc(arr)}]
    try:
        for case in corpus["cases"]:
            meta = {k: manifest if v == "$MANIFEST" else v
                    for k, v in case["meta"].items()}
            tensors = (arr,) * case["tensors"]
            if case["expect"] == "ok":
                _, reply = rpc(meta, tensors)
                assert reply["ok"] is True, case["name"]
            else:
                with pytest.raises(RemoteCallError, match=case["match"]):
                    rpc(meta, tensors)
                    raise AssertionError(
                        f"hostile handoff meta accepted: {case['name']}"
                    )
        # NO partial install survived any of it
        assert "h.0" not in srv.experts
        assert srv.handoff._sessions == {}
        assert srv.handoff.received == 0
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# drain RPC (control plane) + stats surface
# ---------------------------------------------------------------------------


def test_drain_rpc_migrates_and_reports_state():
    from learning_at_home_tpu.client.rpc import client_loop, pool_registry

    srv_a = Server.create(
        expert_uids=["rp.0"], hidden_dim=8, host="127.0.0.1",
        optimizer=optax.sgd(0.01), dht=None,
    )
    srv_b = Server.create(
        num_experts=0, hidden_dim=8, host="127.0.0.1",
        optimizer=optax.sgd(0.01), dht=None,
    )
    try:
        pool = pool_registry().get(srv_a.endpoint)
        _, meta = client_loop().run(
            pool.rpc(
                "drain", (),
                {"successor": [srv_b.endpoint[0], srv_b.endpoint[1]],
                 "grace": 0.0},
                timeout=10.0,
            )
        )
        assert meta["draining"] is True and meta["started"] is True
        assert srv_a.wait_drained(timeout=20.0)
        # stats RPC surfaces the lifecycle section (lah_top's source)
        _, stats = client_loop().run(
            pool.rpc("stats", (), {}, timeout=10.0)
        )
        lc = stats["lifecycle"]
        assert lc["state"] == lifecycle.DRAINED
        assert lc["drain_summary"]["handed_off"] == ["rp.0"]
        assert "rp.0" in srv_b.experts
        # a second drain RPC is a no-op (started=False), not an error
        _, meta2 = client_loop().run(
            pool.rpc("drain", (), {}, timeout=10.0)
        )
        assert meta2["started"] is False
    finally:
        srv_a.shutdown()
        srv_b.shutdown()


# ---------------------------------------------------------------------------
# graceful drain during ACTIVE training: zero quorum failures, zero drops
# ---------------------------------------------------------------------------


def test_drain_during_active_dispatch_zero_failures():
    """The acceptance contract: draining one of two servers while a
    trainer keeps stepping causes ZERO quorum failures and ZERO dropped
    samples — dispatch steers to the successor, which serves the
    migrated experts."""
    import jax.numpy as jnp

    from learning_at_home_tpu.client.moe import RemoteMixtureOfExperts

    boot = DHT()
    d_a = DHT(initial_peers=[boot.endpoint])
    d_b = DHT(initial_peers=[boot.endpoint])
    d_c = DHT(initial_peers=[boot.endpoint])
    srv_a = Server.create(
        expert_uids=["lc.0", "lc.1"], hidden_dim=16, host="127.0.0.1",
        optimizer=optax.adam(1e-3), dht=d_a, update_period=0.4,
    )
    srv_b = Server.create(
        expert_uids=["lc.2", "lc.3"], hidden_dim=16, host="127.0.0.1",
        optimizer=optax.adam(1e-3), dht=d_b, update_period=0.4,
    )
    moe = None
    try:
        moe = RemoteMixtureOfExperts(
            in_features=16, grid_size=(4,), uid_prefix="lc", source=d_c,
            k_best=3, k_min=1, timeout_after_k_min=0.5,
            forward_timeout=20.0, backward_timeout=20.0, alive_ttl=0.4,
        )
        deadline = time.time() + 30
        while time.time() < deadline:
            if len(d_c._loop.run(d_c._get_alive("lc"))) == 4:
                break
            time.sleep(0.2)
        gate = moe.init_gate_params(jax.random.PRNGKey(0))
        opt = optax.adam(1e-2)
        opt_state = opt.init(gate)
        rs = np.random.RandomState(0)
        X = rs.randn(64, 16).astype(np.float32)
        Y = np.roll(X, 1, axis=1)

        def loss_fn(gate, x, y):
            return jnp.mean((moe(x, gate) - y) ** 2)

        failures = 0
        drained = False
        for step in range(30):
            if step == 8:
                assert srv_a.start_drain(
                    successor=srv_b.endpoint, grace=0.5,
                    quiesce_timeout=5.0,
                )
            if not drained and srv_a.wait_drained(timeout=0.0):
                drained = True
                srv_a.shutdown()  # the drained process exits
            idx = rs.randint(0, len(X), 8)
            x, y = jnp.asarray(X[idx]), jnp.asarray(Y[idx])
            try:
                loss, grads = jax.value_and_grad(loss_fn)(gate, x, y)
                updates, opt_state = opt.update(grads, opt_state)
                gate = optax.apply_updates(gate, updates)
            except Exception:
                failures += 1
        assert srv_a.wait_drained(timeout=30.0), "drain never finished"
        assert failures == 0, f"{failures} quorum failures during drain"
        assert moe.samples_dropped == 0
        assert moe.backward_samples_dropped == 0
        # the successor took over the migrated experts
        assert {"lc.0", "lc.1"} <= set(srv_b.experts)
        assert srv_b.handoff.received == 2
    finally:
        if moe is not None:
            del moe
        for srv in (srv_a, srv_b):
            try:
                srv.shutdown()
            except Exception:
                pass
        for d in (d_a, d_b, d_c, boot):
            d.shutdown()


# ---------------------------------------------------------------------------
# restart-from-checkpoint: a hard-killed server rejoins from its last step
# ---------------------------------------------------------------------------


def test_restart_from_checkpoint_rejoins_and_counts_restart(tmp_path):
    """Hard-kill recovery: periodic snapshots via CheckpointManager, a
    fresh process restores the latest complete step BITWISE (identical
    params+opt state ⇒ within < 1 step of quality by construction),
    rejoins the DHT, and the restart counter increments."""
    from learning_at_home_tpu.utils.checkpoint import CheckpointManager

    root = str(tmp_path / "ckpt")
    boot = DHT()
    d_1 = DHT(initial_peers=[boot.endpoint])
    d_2 = DHT(initial_peers=[boot.endpoint])
    d_c = DHT(initial_peers=[boot.endpoint])
    srv1 = Server.create(
        expert_uids=["rs.0"], hidden_dim=8, host="127.0.0.1",
        optimizer=optax.adam(1e-3), dht=d_1, update_period=0.4,
    )
    srv2 = None
    try:
        x = np.random.RandomState(3).randn(2, 8).astype(np.float32)
        srv1.experts["rs.0"].backward([x], [np.ones((2, 8), np.float32)])
        mgr = CheckpointManager(root, keep_last=2)
        step = mgr.save_now(lambda s: srv1.save_checkpoint(root, s))
        assert step == 1
        want = srv1.experts["rs.0"].state_dict()
        fwd_before = np.asarray(srv1.experts["rs.0"].forward([x])[0])
        # hard kill: no drain, no final checkpoint
        srv1.shutdown()
        d_1.shutdown()

        # the relaunched process: fresh params, restore, rejoin, count
        srv2 = Server.create(
            expert_uids=["rs.0"], hidden_dim=8, host="127.0.0.1",
            optimizer=optax.adam(1e-3), dht=d_2, update_period=0.4,
        )
        restored_step = srv2.load_checkpoint(root)
        mgr2 = CheckpointManager(root, keep_last=2)
        srv2.restarts = mgr2.record_restart()
        assert restored_step == 1
        assert srv2.restarts == 1
        got = srv2.experts["rs.0"].state_dict()
        assert_state_bitwise(want, got)
        np.testing.assert_array_equal(
            fwd_before, np.asarray(srv2.experts["rs.0"].forward([x])[0])
        )
        assert srv2.lifecycle_info()["restarts"] == 1
        # rejoined: discoverable through the DHT again
        deadline = time.time() + 10
        alive = {}
        while time.time() < deadline:
            alive = d_c._loop.run(d_c._get_alive("rs"))
            if "rs.0" in alive:
                break
            time.sleep(0.2)
        assert "rs.0" in alive
        # a second restart keeps counting
        assert CheckpointManager(root).record_restart() == 2
    finally:
        if srv2 is not None:
            srv2.shutdown()
        for d in (d_2, d_c, boot):
            d.shutdown()


# ---------------------------------------------------------------------------
# stale-while-revalidate alive cache (the dispatch path must never block
# on a discovery lookup under churn)
# ---------------------------------------------------------------------------


def test_alive_cache_stale_while_revalidate():
    import asyncio

    from learning_at_home_tpu.client.routing import CachedAliveSet
    from learning_at_home_tpu.utils.asyncio_utils import BackgroundLoop

    class Source:
        def __init__(self):
            self.calls = 0
            self.delay = 0.0
            self.fail = False
            self.result = {"a.0": ("h", 1)}

        async def get_alive_experts(self, prefix):
            self.calls += 1
            if self.delay:
                await asyncio.sleep(self.delay)
            if self.fail:
                raise RuntimeError("lookup stalled out")
            return dict(self.result)

    src = Source()
    cache = CachedAliveSet(src, "a", ttl=0.05, swr=True)
    loop = BackgroundLoop(name="test-swr")
    try:
        # first discovery has nothing to serve stale: it blocks
        assert loop.run(cache.get()) == {"a.0": ("h", 1)}
        assert src.calls == 1
        time.sleep(0.08)  # expire the window
        src.result = {"a.1": ("h", 2)}
        src.delay = 0.5
        # stale window + slow lookup: get() must return the STALE set
        # immediately, NOT block for the 500 ms lookup
        t0 = time.monotonic()
        got = loop.run(cache.get())
        assert time.monotonic() - t0 < 0.25, "swr get blocked on the lookup"
        assert got == {"a.0": ("h", 1)}
        assert cache.stale_serves == 1
        # the background refresh lands the new set
        deadline = time.time() + 5
        while time.time() < deadline:
            if loop.run(cache.get()) == {"a.1": ("h", 2)}:
                break
            time.sleep(0.05)
        assert loop.run(cache.get()) == {"a.1": ("h", 2)}
        # a FAILED background refresh keeps the stale set and counts
        time.sleep(0.08)
        src.delay, src.fail = 0.0, True
        assert loop.run(cache.get()) == {"a.1": ("h", 2)}
        deadline = time.time() + 5
        while time.time() < deadline and cache.refresh_failures == 0:
            time.sleep(0.02)
        assert cache.refresh_failures >= 1
        assert loop.run(cache.get()) == {"a.1": ("h", 2)}
        # force_refresh still blocks for an authoritative read
        src.fail = False
        src.result = {"a.2": ("h", 3)}
        assert loop.run(cache.get(force_refresh=True)) == {"a.2": ("h", 3)}
    finally:
        loop.shutdown()

    # swr defaults ON since ISSUE 11 (cheap refreshes); pinning
    # swr=False restores the historical blocking-refresh semantics
    # chaos tests that reason about kill visibility rely on
    assert CachedAliveSet(Source(), "a", ttl=0.05).swr is True
    src2 = Source()
    cache2 = CachedAliveSet(src2, "a", ttl=0.05, swr=False)
    assert cache2.swr is False
    loop2 = BackgroundLoop(name="test-noswr")
    try:
        assert loop2.run(cache2.get()) == {"a.0": ("h", 1)}
        time.sleep(0.08)
        src2.result = {"a.1": ("h", 2)}
        assert loop2.run(cache2.get()) == {"a.1": ("h", 2)}  # blocked+fresh
        assert cache2.stale_serves == 0
    finally:
        loop2.shutdown()


# ---------------------------------------------------------------------------
# lah_top lifecycle rendering (pure)
# ---------------------------------------------------------------------------


def test_lah_top_renders_lifecycle_columns():
    import importlib

    lah_top = importlib.import_module("tools.lah_top")

    def row(peer_id, lifecycle_section):
        return {
            "peer_id": peer_id, "role": "server",
            "endpoint": ("127.0.0.1", 1), "expires_at": 0.0,
            "snapshot": {"lifecycle": lifecycle_section, "metrics": {}},
        }

    rows = [
        row("srv-serving", {"state": "SERVING", "uptime_s": 12.3,
                            "restarts": 2}),
        row("srv-draining", {"state": "DRAINING", "uptime_s": 5.0,
                             "restarts": 0}),
        {"peer_id": "trainer-1", "role": "trainer",
         "endpoint": ("127.0.0.1", 2), "expires_at": 0.0, "snapshot": {}},
    ]
    out = lah_top.render(rows, "swarm", dead={"srv-gone"})
    assert "STATE" in out and "UPTIME" in out and "RST" in out
    assert "SERVING" in out and "DRAINING" in out
    assert "12s" in out  # uptime rendered in seconds
    assert "DEAD" in out and "record expired" in out
    # malformed lifecycle sections render dashes, never crash
    rows.append(row("srv-weird", {"state": 42}))
    assert "srv-weird" in lah_top.render(rows, "swarm", dead=set())
