"""Scale tests: 4096-expert grid routing ([BJ] config 4 dimensions) and a
true multi-process server (SURVEY §4: multi-process-on-localhost)."""

import os
import subprocess
import sys
import time

import numpy as np
import pytest

from learning_at_home_tpu.client.routing import (
    StaticExpertSource,
    beam_search_alive,
    make_uid,
    select_top_k,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_select_top_k_4096_experts():
    """Full-enumeration selection stays fast and exact at the 4096 grid."""
    rs = np.random.RandomState(0)
    grid = (64, 64)
    uids = [make_uid("big", (i, j)) for i in range(64) for j in range(64)]
    logits = [rs.randn(32, 64).astype(np.float32) for _ in range(2)]
    t0 = time.monotonic()
    sel, coords = select_top_k(logits, uids, k=4)
    elapsed = time.monotonic() - t0
    assert sel.shape == (32, 4)
    assert elapsed < 5.0, f"selection took {elapsed:.2f}s for 4096 experts"
    # exact: verify one sample against brute force
    scores = logits[0][7][:, None] + logits[1][7][None, :]
    best = np.argsort(-scores.ravel())[:4]
    got = {tuple(coords[s]) for s in sel[7]}
    want = {(b // 64, b % 64) for b in best}
    assert got == want


def test_beam_search_4096_reads_few_records():
    """Beam routing touches only beam_size prefix records, not the grid."""
    import asyncio

    experts = {
        make_uid("big", (i, j)): ("h", 1) for i in range(64) for j in range(64)
    }

    class CountingSource(StaticExpertSource):
        def __init__(self, experts):
            super().__init__(experts)
            self.reads = 0

        async def get_alive_experts(self, prefix):
            self.reads += 1
            return await super().get_alive_experts(prefix)

    source = CountingSource(experts)
    rs = np.random.RandomState(1)
    logits = [rs.randn(16, 64).astype(np.float32), rs.randn(16, 64).astype(np.float32)]
    alive = asyncio.run(
        beam_search_alive(source, "big", logits, (64, 64), beam_size=4)
    )
    assert source.reads <= 4 * 16  # ≤ beam_size rows per sample, deduped
    assert 64 <= len(alive) <= 4 * 16 * 64  # plausible candidate set
    for uid in alive:
        assert uid.startswith("big.")


@pytest.mark.slow
def test_multiprocess_server_roundtrip():
    """Launch the server CLI as a REAL separate process and call it."""
    from learning_at_home_tpu.utils.subproc import clean_jax_subprocess_env

    env = clean_jax_subprocess_env(REPO)
    port = 43219
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "learning_at_home_tpu.server",
            "--num-experts", "1", "--hidden-dim", "8",
            "--port", str(port), "--no-dht",
        ],
        env=env,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )
    try:
        from learning_at_home_tpu.client import RemoteExpert, reset_client_rpc
        from learning_at_home_tpu.utils.connection import RemoteCallError

        deadline = time.time() + 60
        out = None
        expert = RemoteExpert("expert.0", ("127.0.0.1", port), timeout=10.0)
        while time.time() < deadline:
            try:
                out = expert.forward_blocking([np.ones((2, 8), np.float32)])
                break
            except (OSError, RemoteCallError):
                if proc.poll() is not None:
                    raise AssertionError(
                        f"server died: {proc.stdout.read()[-2000:]}"
                    )
                time.sleep(1.0)
        assert out is not None and out[0].shape == (2, 8)
        reset_client_rpc()
    finally:
        proc.terminate()
        proc.wait(timeout=30)
