"""M1 tests (BASELINE config 2): RemoteMixtureOfExperts, 4 FFN experts,
top-2 gating, single host — plus the k-of-n fault-tolerance path."""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from learning_at_home_tpu.client import reset_client_rpc
from learning_at_home_tpu.client.moe import MoEDispatchError, RemoteMixtureOfExperts
from learning_at_home_tpu.client.routing import (
    StaticExpertSource,
    make_uid,
    select_top_k,
    split_uid,
)
from learning_at_home_tpu.server.server import background_server

HID = 16


def test_uid_helpers():
    assert make_uid("ffn", (4, 17)) == "ffn.4.17"
    assert split_uid("ffn.4.17") == ("ffn", (4, 17))
    assert split_uid("expert.3") == ("expert", (3,))


def test_select_top_k():
    rs = np.random.RandomState(0)
    logits = [rs.randn(5, 4).astype(np.float32), rs.randn(5, 3).astype(np.float32)]
    uids = [make_uid("g", (i, j)) for i in range(4) for j in range(3)]
    sel, coords = select_top_k(logits, uids, k=3)
    assert sel.shape == (5, 3)
    # brute force check
    for b in range(5):
        scores = np.array([logits[0][b, i] + logits[1][b, j] for i, j in coords])
        best = np.argsort(-scores)[:3]
        np.testing.assert_array_equal(np.sort(scores[sel[b]]), np.sort(scores[best]))
        assert scores[sel[b, 0]] >= scores[sel[b, 1]] >= scores[sel[b, 2]]


@pytest.fixture(scope="module")
def moe_server():
    with background_server(
        num_experts=4, hidden_dim=HID, expert_prefix="ffn", seed=7
    ) as (endpoint, srv):
        source = StaticExpertSource({uid: endpoint for uid in srv.experts})
        yield endpoint, srv, source
    reset_client_rpc()


def _local_outputs(srv, x):
    """Each expert's output under its live server-side params."""
    outs = {}
    for uid, backend in srv.experts.items():
        params = backend.state_dict()["params"]
        outs[uid] = np.asarray(backend.apply_fn(params, x))
    return outs


def test_moe_forward_full_mixture(moe_server):
    """k_best = all experts: output must equal the full softmax mixture."""
    endpoint, srv, source = moe_server
    moe = RemoteMixtureOfExperts(
        in_features=HID, grid_size=(4,), uid_prefix="ffn", source=source,
        k_best=4, k_min=4,
    )
    gate = moe.init_gate_params(jax.random.PRNGKey(0))
    x = np.random.RandomState(1).randn(6, HID).astype(np.float32)

    local = _local_outputs(srv, x)
    out = np.asarray(moe(jnp.asarray(x), gate))

    logits = np.concatenate(
        [np.asarray(x @ gate["w0"])], axis=1
    )  # [6, 4], one grid dim
    w = jax.nn.softmax(jnp.asarray(logits), axis=-1)
    expected = np.zeros_like(x)
    for i in range(4):
        expected += np.asarray(w[:, i : i + 1]) * local[f"ffn.{i}"]
    np.testing.assert_allclose(out, expected, atol=1e-4, rtol=1e-4)


def test_moe_top2_selects_best(moe_server):
    endpoint, srv, source = moe_server
    moe = RemoteMixtureOfExperts(
        in_features=HID, grid_size=(4,), uid_prefix="ffn", source=source,
        k_best=2, k_min=2,
    )
    gate = moe.init_gate_params(jax.random.PRNGKey(3))
    x = np.random.RandomState(2).randn(5, HID).astype(np.float32)
    local = _local_outputs(srv, x)

    out = np.asarray(moe(jnp.asarray(x), gate))

    logits = np.asarray(x @ np.asarray(gate["w0"]))  # [5, 4]
    expected = np.zeros_like(x)
    for b in range(5):
        top2 = np.argsort(-logits[b])[:2]
        w = jax.nn.softmax(jnp.asarray(logits[b, top2]))
        for wi, i in zip(np.asarray(w), top2):
            expected[b] += wi * local[f"ffn.{i}"][b]
    np.testing.assert_allclose(out, expected, atol=1e-4, rtol=1e-4)


def test_moe_grad_flows_and_updates_experts(moe_server):
    endpoint, srv, source = moe_server
    # generous grace: the update_count assertion below needs EVERY backward
    # RPC to land, so slow-box stragglers must not be cancelled mid-test
    moe = RemoteMixtureOfExperts(
        in_features=HID, grid_size=(4,), uid_prefix="ffn", source=source,
        k_best=4, k_min=4, backward_k_min=1, timeout_after_k_min=15.0,
    )
    gate = moe.init_gate_params(jax.random.PRNGKey(5))
    x = jnp.asarray(np.random.RandomState(3).randn(4, HID).astype(np.float32))

    updates_before = {uid: b.update_count for uid, b in srv.experts.items()}

    def loss(gate, x):
        return jnp.sum(moe(x, gate) ** 2)

    ggate, gx = jax.grad(loss, argnums=(0, 1))(gate, x)
    # gate grads are nonzero (gradient through softmax-weighted mixture)
    assert float(jnp.abs(ggate["w0"]).sum()) > 0
    # x grads are nonzero (gradient through the backward RPCs)
    assert float(jnp.abs(gx).sum()) > 0
    # every expert participated → every expert applied its async update
    for uid, b in srv.experts.items():
        assert b.update_count == updates_before[uid] + 1, uid


def test_moe_under_jit(moe_server):
    endpoint, srv, source = moe_server
    moe = RemoteMixtureOfExperts(
        in_features=HID, grid_size=(4,), uid_prefix="ffn", source=source,
        k_best=2, k_min=1,
    )
    gate = moe.init_gate_params(jax.random.PRNGKey(6))

    @jax.jit
    def step(x, gate):
        return moe(x, gate).sum()

    x = jnp.ones((3, HID), jnp.float32)
    v1 = step(x, gate)
    v2 = step(x, gate)  # compiled-cache path
    np.testing.assert_allclose(float(v1), float(v2), rtol=1e-5)


def test_moe_fault_tolerance_dead_server():
    """One of two servers dies; k_min=1 dispatch must still return."""
    with background_server(
        num_experts=2, hidden_dim=HID, expert_prefix="ffn", seed=11
    ) as (ep_alive, srv_alive):
        with background_server(
            num_experts=2, hidden_dim=HID, expert_prefix="dead", seed=12
        ) as (ep_dead, _):
            pass  # exits immediately → server is down, port is stale
        source = StaticExpertSource(
            {
                "ffn.0": ep_alive,
                "ffn.1": ep_alive,
                # grid coords 2,3 point at the dead endpoint
                "ffn.2": ep_dead,
                "ffn.3": ep_dead,
            }
        )
        moe = RemoteMixtureOfExperts(
            in_features=HID, grid_size=(4,), uid_prefix="ffn", source=source,
            k_best=4, k_min=1, timeout_after_k_min=0.2, forward_timeout=2.0,
        )
        gate = moe.init_gate_params(jax.random.PRNGKey(0))
        x = np.random.RandomState(5).randn(3, HID).astype(np.float32)
        out = np.asarray(moe(jnp.asarray(x), gate))
        # mixture must equal softmax over the ALIVE experts only
        local = _local_outputs(srv_alive, x)
        logits = np.asarray(x @ np.asarray(gate["w0"]))[:, :2]  # alive coords 0,1
        w = np.asarray(jax.nn.softmax(jnp.asarray(logits), axis=-1))
        expected = w[:, 0:1] * local["ffn.0"] + w[:, 1:2] * local["ffn.1"]
        np.testing.assert_allclose(out, expected, atol=1e-4, rtol=1e-4)
    reset_client_rpc()


def test_moe_per_sample_quorum_degradation():
    """A sample whose only chosen expert is dead is masked to ZERO
    contribution and counted — the step survives; gradients stay finite
    (per-sample degradation, not step-killing; VERDICT r1 item 6)."""
    with background_server(
        num_experts=2, hidden_dim=HID, expert_prefix="ffn", seed=13
    ) as (ep_alive, srv):
        with background_server(
            num_experts=1, hidden_dim=HID, expert_prefix="dead", seed=14
        ) as (ep_dead, _):
            pass  # exits immediately → dead endpoint
        source = StaticExpertSource({"ffn.0": ep_alive, "ffn.1": ep_dead})
        moe = RemoteMixtureOfExperts(
            in_features=HID, grid_size=(2,), uid_prefix="ffn", source=source,
            k_best=1, k_min=1, forward_timeout=1.5, backward_timeout=1.5,
        )
        # deterministic routing: sample 0 → expert 0 (alive),
        # sample 1 → expert 1 (dead)
        w0 = np.zeros((HID, 2), np.float32)
        w0[0, 0] = w0[1, 1] = 10.0
        gate = {"w0": jnp.asarray(w0)}
        x = np.zeros((2, HID), np.float32)
        x[0, 0] = x[1, 1] = 1.0

        out = np.asarray(moe(jnp.asarray(x), gate))
        local = _local_outputs(srv, x)
        np.testing.assert_allclose(out[0], local["ffn.0"][0], atol=1e-4)
        np.testing.assert_allclose(out[1], 0.0)  # dropped, not poisoned
        assert moe.samples_total == 2 and moe.samples_dropped == 1

        # gradients survive too: dead sample contributes zero input-grad
        def loss(gate, x):
            return moe(jnp.asarray(x), gate).sum()

        g = jax.grad(loss)(gate, x)
        assert np.isfinite(np.asarray(g["w0"])).all()
        # the forward-dropped sample's missing grads are EXPECTED — they
        # must not be double-counted as a backward failure
        assert moe.backward_samples_dropped == 0
    reset_client_rpc()


def test_moe_quorum_failure_raises():
    """All experts dead → MoEDispatchError, not a hang or silent zero."""
    with background_server(num_experts=1, hidden_dim=HID, expert_prefix="x") as (
        ep,
        _,
    ):
        pass  # dead now
    source = StaticExpertSource({"x.0": ep})
    moe = RemoteMixtureOfExperts(
        in_features=HID, grid_size=(1,), uid_prefix="x", source=source,
        k_best=1, k_min=1, forward_timeout=1.5,
    )
    gate = moe.init_gate_params(jax.random.PRNGKey(0))
    with pytest.raises(Exception):  # XLA wraps the MoEDispatchError
        np.asarray(moe(jnp.ones((2, HID), jnp.float32), gate))
    reset_client_rpc()


class TestWireDtype:
    """bf16 wire compression (round-3 verdict task 4): payloads downcast
    on the wire both directions, math still f32 on both ends."""

    def test_forward_parity_and_backward_runs(self, moe_server):
        endpoint, srv, source = moe_server
        kw = dict(
            in_features=HID, grid_size=(4,), uid_prefix="ffn",
            source=source, k_best=2, k_min=2,
        )
        moe32 = RemoteMixtureOfExperts(**kw)
        moe16 = RemoteMixtureOfExperts(**kw, wire_dtype="bfloat16")
        gate = moe32.init_gate_params(jax.random.PRNGKey(0))
        x = np.random.RandomState(3).randn(8, HID).astype(np.float32)

        y32 = np.asarray(moe32(jnp.asarray(x), gate))
        y16 = np.asarray(moe16(jnp.asarray(x), gate))
        # bf16 keeps ~8 mantissa bits: outputs agree to bf16 resolution
        np.testing.assert_allclose(y16, y32, rtol=0.05, atol=0.05)
        assert not np.allclose(y16, 0.0)

        # backward (fires the server's async optimizer step) must run and
        # produce finite input-grads through the compressed wire
        def loss(gate, x):
            return jnp.sum(moe16(x, gate) ** 2)

        g = jax.grad(loss, argnums=1)(gate, jnp.asarray(x))
        assert np.isfinite(np.asarray(g)).all()

    def test_remote_expert_wire_dtype(self, moe_server):
        from learning_at_home_tpu.client import RemoteExpert

        endpoint, srv, source = moe_server
        uid = sorted(srv.experts)[0]
        e32 = RemoteExpert(uid, endpoint)
        e16 = RemoteExpert(uid, endpoint, wire_dtype="bfloat16")
        x = np.random.RandomState(0).randn(4, HID).astype(np.float32)
        y32 = np.asarray(e32.forward_blocking([x])[0])
        reply = e16.forward_blocking([x])[0]
        # server downcasts its reply to the wire dtype
        assert reply.dtype == np.dtype("bfloat16")
        np.testing.assert_allclose(
            np.asarray(reply, np.float32), y32, rtol=0.05, atol=0.05
        )

    def test_bad_wire_dtype_rejected(self, moe_server):
        endpoint, srv, source = moe_server
        with pytest.raises(ValueError, match="wire_dtype"):
            RemoteMixtureOfExperts(
                in_features=HID, grid_size=(4,), uid_prefix="ffn",
                source=source, wire_dtype="float64",
            )


def test_select_top_k_bias_steers_selection():
    """Selection bias (latency-aware routing) flips near-ties without
    touching the caller's score space."""
    logits = [np.tile([0.2, 0.0, 0.0, 0.5], (3, 1)).astype(np.float32)]
    uids = [make_uid("b", (i,)) for i in range(4)]
    sel0, _ = select_top_k(logits, uids, k=1)
    assert (sel0 == 3).all()  # best gate score wins unbiased
    bias = np.asarray([0.0, 0.0, 0.0, -1.0], np.float32)  # slow peer
    sel, _ = select_top_k(logits, uids, k=1, bias=bias)
    assert (sel == 0).all()  # the penalty outweighs the 0.3 gate edge


class TestLatencyAwareRouting:
    """latency_weight: selection learns to avoid a slow peer (cf. the
    topology-/placement-aware MoE serving literature)."""

    def _run(self, latency_weight: float) -> tuple[list, list]:
        from learning_at_home_tpu.server import ChaosConfig

        slow_chaos = ChaosConfig(base_latency=0.25, seed=0)
        with background_server(
            num_experts=2, hidden_dim=HID, expert_prefix="lat", seed=1
        ) as (fast_ep, fast_srv):
            with background_server(
                num_experts=2, hidden_dim=HID, expert_prefix="lat",
                expert_offset=2, seed=2, chaos=slow_chaos,
            ) as (slow_ep, slow_srv):
                experts = {uid: fast_ep for uid in fast_srv.experts}
                experts.update({uid: slow_ep for uid in slow_srv.experts})
                moe = RemoteMixtureOfExperts(
                    in_features=HID, grid_size=(4,), uid_prefix="lat",
                    source=StaticExpertSource(experts), k_best=2, k_min=1,
                    timeout_after_k_min=2.0,
                    latency_weight=latency_weight,
                )
                gate = moe.init_gate_params(jax.random.PRNGKey(0))
                rs = np.random.RandomState(0)
                for _ in range(8):
                    x = jnp.asarray(rs.randn(6, HID).astype(np.float32))
                    moe(x, gate)
                times = list(moe.dispatch_times)
                selections = list(moe.selection_log)
        reset_client_rpc()
        return times, selections

    SLOW_UIDS = frozenset({"lat.2", "lat.3"})

    def test_latency_weight_learns_to_avoid_slow_peer(self):
        aware_t, aware_sel = self._run(latency_weight=20.0)
        blind_t, blind_sel = self._run(latency_weight=0.0)
        # THE MECHANISM (primary, clock-free): the first dispatches probe
        # both peers (EMA warmup); once the slow peer's ~0.25 s EMA is
        # learned its selection score drops by ~5, so the LAST dispatches
        # must not select its experts at all — while the unbiased control
        # keeps picking them (the gate's scores alone are topology-blind)
        late_aware = frozenset().union(*aware_sel[-3:])
        late_blind = frozenset().union(*blind_sel[-3:])
        assert not (late_aware & self.SLOW_UIDS), (aware_sel, late_aware)
        assert late_blind & self.SLOW_UIDS, (blind_sel, late_blind)
        # the clock (secondary, generous): routing around the slow peer
        # must actually be cheaper than paying its injected latency —
        # a relative bound only; absolute wall-clock varies with box load
        assert np.mean(aware_t[-3:]) < np.mean(blind_t[-3:]), (
            aware_t, blind_t,
        )
