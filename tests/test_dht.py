"""M2 tests: Kademlia DHT — unit tier (routing table) + localhost swarm
integration (store/get across nodes, expiry, expert declare/discover),
mirroring the reference's test_dht.py strategy (SURVEY.md §4)."""

import asyncio
import time

import pytest

from learning_at_home_tpu.dht import DHT, DHTNode, uid_prefixes
from learning_at_home_tpu.dht.protocol import DHTRecordStorage, PLAIN_SUBKEY
from learning_at_home_tpu.dht.routing import DHTID, KBucket, RoutingTable
from learning_at_home_tpu.utils.timed_storage import get_dht_time


# ---------------- unit tier ----------------


def test_dhtid():
    a, b = DHTID.generate(), DHTID.generate()
    assert a != b
    assert a.xor_distance(a) == 0
    assert a.xor_distance(b) == b.xor_distance(a)
    assert DHTID.from_bytes(a.to_bytes()) == a
    assert DHTID.from_key("expert.1") == DHTID.from_key("expert.1")
    assert DHTID.from_key("expert.1") != DHTID.from_key("expert.2")


def test_kbucket_lru_and_replacement():
    bucket = KBucket(0, 2**160, k=3)
    ids = [DHTID(i + 1) for i in range(5)]
    for i, nid in enumerate(ids[:3]):
        assert bucket.add_or_update(nid, ("h", i))
    assert not bucket.add_or_update(ids[3], ("h", 3))  # full → replacement
    assert ids[3] in bucket.replacement
    # refresh moves to LRU tail
    bucket.add_or_update(ids[0], ("h", 0))
    assert bucket.oldest[0] == ids[1]
    # removal promotes from replacement
    bucket.remove(ids[1])
    assert ids[3] in bucket.peers and ids[1] not in bucket.peers
    # removing a REPLACEMENT node must not promote anything (esp. itself)
    assert bucket.add_or_update(ids[4], ("h", 4)) is False
    bucket.remove(ids[4])
    assert ids[4] not in bucket.peers and ids[4] not in bucket.replacement
    assert len(bucket.peers) == 3


def test_routing_table_split_and_nearest():
    own = DHTID(2**159)  # sits in the upper half
    table = RoutingTable(own, bucket_size=4)
    for i in range(64):
        table.add_or_update_node(DHTID.from_key(f"n{i}"), ("h", i))
    assert len(table.buckets) > 1
    assert len(table) > 4
    target = DHTID.from_key("target")
    nearest = table.nearest_neighbors(target, 5)
    assert len(nearest) == 5
    dists = [int(nid) ^ int(target) for nid, _ in nearest]
    assert dists == sorted(dists)
    # exhaustive check: these really are the closest known
    all_dists = sorted(
        int(nid) ^ int(target) for b in table.buckets for nid in b.peers
    )
    assert dists == all_dists[:5]


def test_record_storage_subkeys(monkeypatch):
    now = [100.0]
    monkeypatch.setattr(
        "learning_at_home_tpu.utils.timed_storage.get_dht_time", lambda: now[0]
    )
    st = DHTRecordStorage()
    assert st.store(b"k", "a", 1, 110.0)
    assert st.store(b"k", "b", 2, 120.0)
    assert not st.store(b"k", "a", 0, 105.0)  # older expiration loses
    assert st.get(b"k") == {"a": (1, 110.0), "b": (2, 120.0)}
    now[0] = 115.0
    assert st.get(b"k") == {"b": (2, 120.0)}  # 'a' expired individually


def test_uid_prefixes():
    assert uid_prefixes("ffn.4.17") == ["ffn", "ffn.4"]
    assert uid_prefixes("expert.3") == ["expert"]


# ---------------- swarm tier (real protocol traffic on localhost) ----------------


def run(coro):
    return asyncio.run(coro)


async def make_swarm(n, **kwargs):
    first = await DHTNode.create(**kwargs)
    nodes = [first]
    for _ in range(n - 1):
        nodes.append(
            await DHTNode.create(initial_peers=[first.endpoint], **kwargs)
        )
    return nodes


async def teardown(nodes):
    await asyncio.gather(*(n.shutdown() for n in nodes))


def test_swarm_store_get_across_nodes():
    async def main():
        nodes = await make_swarm(8, bucket_size=4)
        try:
            ok = await nodes[2].store("the-key", [1, 2, 3], get_dht_time() + 30)
            assert ok
            # a DIFFERENT node must find the value via iterative lookup
            rec = await nodes[7].get("the-key")
            assert rec[PLAIN_SUBKEY][0] == [1, 2, 3]
            # a key nobody stored is absent
            assert await nodes[5].get("missing-key") == {}
        finally:
            await teardown(nodes)

    run(main())


def test_swarm_expiration_is_failure_detection():
    async def main():
        nodes = await make_swarm(4, bucket_size=4)
        try:
            await nodes[0].store("ephemeral", "v", get_dht_time() + 0.5)
            assert (await nodes[3].get("ephemeral"))[PLAIN_SUBKEY][0] == "v"
            await asyncio.sleep(0.6)
            assert await nodes[3].get("ephemeral") == {}  # gone ⇒ 'dead'
        finally:
            await teardown(nodes)

    run(main())


def test_swarm_subkey_merge_from_different_writers():
    """Two servers declare under one prefix key; readers see both."""

    async def main():
        nodes = await make_swarm(5, bucket_size=4)
        try:
            exp = get_dht_time() + 30
            await nodes[1].store("ffn", ["hostA", 1], exp, subkey="ffn.0")
            await nodes[2].store("ffn", ["hostB", 2], exp, subkey="ffn.1")
            rec = await nodes[4].get("ffn")
            assert rec["ffn.0"][0] == ["hostA", 1]
            assert rec["ffn.1"][0] == ["hostB", 2]
        finally:
            await teardown(nodes)

    run(main())


def test_maintenance_evicts_dead_peer_and_refreshes():
    async def main():
        a = await DHTNode.create(bucket_size=4, maintenance_period=None)
        b = await DHTNode.create(
            initial_peers=[a.endpoint], bucket_size=4, maintenance_period=None
        )
        c = await DHTNode.create(
            initial_peers=[a.endpoint], bucket_size=4, maintenance_period=None
        )
        try:
            assert len(b.routing_table) >= 2
            await c.shutdown()  # c dies
            b.start_maintenance(period=0.3)
            deadline = asyncio.get_running_loop().time() + 10
            while asyncio.get_running_loop().time() < deadline:
                if b.routing_table.get_endpoint(c.node_id) is None:
                    break
                await asyncio.sleep(0.2)
            assert b.routing_table.get_endpoint(c.node_id) is None, (
                "dead peer never evicted by maintenance"
            )
            assert b.routing_table.get_endpoint(a.node_id) is not None
        finally:
            await teardown([a, b])

    run(main())


def test_node_failure_lookup_still_works():
    async def main():
        nodes = await make_swarm(6, bucket_size=4)
        try:
            await nodes[0].store("durable", 42, get_dht_time() + 30)
            # kill two nodes; replication across k closest should survive
            await nodes[1].shutdown()
            await nodes[2].shutdown()
            rec = await nodes[5].get("durable")
            assert rec and rec[PLAIN_SUBKEY][0] == 42
        finally:
            await teardown([nodes[0], *nodes[3:]])

    run(main())


# ---------------- DHT facade (thread-bridged) ----------------


def test_dht_facade_declare_and_discover():
    dht1 = DHT()
    dht2 = DHT(initial_peers=[dht1.endpoint])
    try:
        n = dht1.declare_experts_sync(
            ["ffn.0.0", "ffn.0.1", "ffn.1.1"], ("10.0.0.1", 9000), expiration=30
        )
        assert n == 3
        # full-uid resolution from the OTHER node
        eps = dht2.get_experts_sync(["ffn.0.1", "ffn.9.9"])
        assert eps["ffn.0.1"] == ("10.0.0.1", 9000)
        assert eps["ffn.9.9"] is None
        # enumeration via top-level prefix record
        alive = dht2._loop.run(dht2._get_alive("ffn"))
        assert set(alive) == {"ffn.0.0", "ffn.0.1", "ffn.1.1"}
        # beam-search primitive: which sub-prefixes are active
        active = dht2._loop.run(dht2._first_k_active(["ffn.0", "ffn.7", "ffn.1"], 2))
        assert active["ffn.0"] is True
        assert active["ffn.7"] is False
        assert active["ffn.1"] is True
    finally:
        dht2.shutdown()
        dht1.shutdown()


def test_dht_facade_bridge_from_foreign_loop():
    """The async API must work when awaited from a different event loop."""
    dht = DHT()
    try:
        async def foreign():
            await dht.declare_experts(["e.0"], ("1.2.3.4", 5), expiration=10)
            return await dht.get_experts(["e.0"])

        result = asyncio.run(foreign())
        assert result["e.0"] == ("1.2.3.4", 5)
    finally:
        dht.shutdown()


def test_record_storage_bounded():
    """Both storage tiers are capped: a flood of keys or subkeys evicts
    instead of growing without bound (the swarm is a trust boundary)."""
    st = DHTRecordStorage(maxsize=4, max_subkeys=3)
    exp = get_dht_time() + 30
    stored = [st.store(f"k{i}".encode(), PLAIN_SUBKEY, [i], exp) for i in range(10)]
    assert all(stored[:4])  # in-bounds stores succeed and say so
    assert len(st) <= 4
    for i in range(10):
        st.store(b"one", f"sk{i}", [i], exp)
    assert len(st.get(b"one")) <= 3


def test_store_rpc_rejects_absurd_keys():
    """Oversized keys/subkeys in a store RPC are refused, not stored."""
    node = asyncio.run(DHTNode.create(maintenance_period=None))
    try:
        meta = {
            "from": DHTID.generate().to_bytes(),
            "port": 1,
            "items": [
                [b"x" * 10_000, PLAIN_SUBKEY, [1], get_dht_time() + 30],
                [b"fine", "s" * 10_000, [1], get_dht_time() + 30],
                [b"fine", "ok", [1], get_dht_time() + 30],
            ],
        }
        reply = node.protocol._serve("store", meta, "127.0.0.1")
        assert reply["ok"]["ok"] is True
        assert sum(bool(v) for v in reply["ok"].values()) == 1
        assert len(node.storage.get(b"fine")) == 1
    finally:
        asyncio.run(node.shutdown())


@pytest.mark.slow
def test_join_covers_distant_regions_at_scale():
    """Regression for the 128-node hit-rate bug, guarded at BOTH layers.

    Mechanism: a node must learn from every peer it HEARS FROM in its own
    lookups (textbook Kademlia), plus the paper's full join (refresh every
    other bucket range).  Before the fixes a late joiner's table held
    exactly ONE peer (the bootstrap node): its own lookups taught it
    nothing, tables stayed neighborhood-thin, and iterative lookups
    converged on local clusters — store() placed records at XOR-ranks
    34-74 of 128 and hit rate fell to 0.973.

    Behavior: at 48 nodes every stored key must be retrievable via a
    cross-node lookup with placement tight around the true closest set
    (post-fix cold-join margins measured at 128 nodes: worst min rank 0,
    worst per-key median 4 — the bounds below have >3x headroom)."""

    async def main():
        import numpy as np

        nodes = await make_swarm(48, bucket_size=8, maintenance_period=None)
        try:
            # --- mechanism: the LAST joiner heard from many peers during
            # its join lookups and must have learned them (pre-fix: 1)
            late_table = len(nodes[-1].routing_table)
            assert late_table >= 8, late_table
            own = int(nodes[-1].node_id)
            prefix_depths = {
                (own ^ int(nid)).bit_length()
                for b in nodes[-1].routing_table.buckets
                for nid in b.peers
            }
            assert len(prefix_depths) >= 3, prefix_depths  # spans regions

            # --- behavior: store/get + placement
            rs = np.random.RandomState(0)
            n_keys = 40
            storer_idx = {}
            for i in range(n_keys):
                storer_idx[i] = rs.randint(48)
                ok = await nodes[storer_idx[i]].store(
                    f"scale-key-{i}", i, get_dht_time() + 60
                )
                assert ok
            for i in range(n_keys):
                # getter must DIFFER from the storer: get() merges local
                # storage, so a same-node draw would bypass the iterative
                # lookup this test regresses
                getter = (storer_idx[i] + 1 + rs.randint(47)) % 48
                rec = await nodes[getter].get(f"scale-key-{i}")
                if not (rec and rec[PLAIN_SUBKEY][0] == i):
                    # one transient RPC timeout under 1-core load can cost
                    # a lookup; a real client retries, so does the test —
                    # the BUG was a deterministic routing failure no retry
                    # could fix
                    await asyncio.sleep(0.5)
                    rec = await nodes[getter].get(f"scale-key-{i}")
                assert rec and rec[PLAIN_SUBKEY][0] == i, f"miss scale-key-{i}"
            bad_placement = []
            for i in range(n_keys):
                target = DHTID.from_key(f"scale-key-{i}")
                ranked = sorted(
                    nodes, key=lambda n: int(n.node_id) ^ int(target)
                )
                holder_ranks = [
                    r for r, n in enumerate(ranked)
                    if n.storage.get(target.to_bytes())
                ]
                if not holder_ranks or min(holder_ranks) >= 4 or (
                    float(np.median(holder_ranks)) >= 12
                ):
                    bad_placement.append((i, holder_ranks))
            # the bug class misplaced essentially every affected key's
            # WHOLE replica set (min rank >= 13); tolerate at most 2 of
            # 40 load-transient outliers, and ONLY near-miss ones — any
            # key whose best replica sits past rank 8 is true
            # misplacement and fails hard regardless of the count
            assert len(bad_placement) <= 2, bad_placement
            for i, hr in bad_placement:
                assert hr and min(hr) < 8, (i, hr)
        finally:
            await teardown(nodes)

    run(main())


def test_lookup_strike_eviction_requires_distinct_lookups():
    """Two-strike lookup eviction: two timeouts from ONE logical event
    (concurrent lookups whose RPCs were in flight during the same pause)
    must not evict; a strike from a distinct, later lookup must."""
    node = DHTNode(node_id=DHTID(2**80))
    peer = DHTID(2**81)
    node.routing_table.add_or_update_node(peer, ("127.0.0.1", 1))

    # lookups A and B issued their RPC waves before either strike landed —
    # one GC pause, two timeouts, ONE logical event: no eviction
    wave_started = time.monotonic()
    node._record_lookup_timeout(peer, lookup_id=1, wave_started=wave_started)
    node._record_lookup_timeout(peer, lookup_id=2, wave_started=wave_started)
    assert node.routing_table.get_endpoint(peer) is not None
    assert peer in node._lookup_strikes

    # a later lookup whose wave went out AFTER the strike was recorded
    # gives the peer a fresh chance; failing it is the real second strike
    node._record_lookup_timeout(
        peer, lookup_id=3, wave_started=time.monotonic()
    )
    assert node.routing_table.get_endpoint(peer) is None
    assert peer not in node._lookup_strikes  # eviction cleared the strike


def test_lookup_strike_same_lookup_never_evicts():
    node = DHTNode(node_id=DHTID(2**80))
    peer = DHTID(2**81)
    node.routing_table.add_or_update_node(peer, ("127.0.0.1", 1))
    node._record_lookup_timeout(peer, lookup_id=7, wave_started=time.monotonic())
    node._record_lookup_timeout(peer, lookup_id=7, wave_started=time.monotonic())
    assert node.routing_table.get_endpoint(peer) is not None


def test_lookup_strikes_cleared_when_node_leaves_table():
    """A peer that times out once and then leaves the table by ANY path
    (e.g. maintenance eviction) must not leak its strike entry."""
    node = DHTNode(node_id=DHTID(2**80))
    peer = DHTID(2**81)
    node.routing_table.add_or_update_node(peer, ("127.0.0.1", 1))
    node._record_lookup_timeout(peer, lookup_id=1, wave_started=time.monotonic())
    assert peer in node._lookup_strikes
    node.routing_table.remove_node(peer)  # the maintenance path
    assert peer not in node._lookup_strikes


# ---------------- routing-record cache (ISSUE 11) ----------------


def test_record_cache_honors_record_expiry():
    """A cached entry is filtered by each RECORD's own expiration: an
    expired subkey never comes out of the cache mid-TTL-window, so DHT
    expiry (the swarm's failure detector) is never blunted by caching."""
    from learning_at_home_tpu.dht import _RecordCache

    cache = _RecordCache(ttl=30.0)
    now = get_dht_time()
    cache.put("k", {"soon": (1, now + 0.2), "later": (2, now + 30)})
    assert set(cache.get("k")) == {"soon", "later"}
    time.sleep(0.25)
    assert set(cache.get("k")) == {"later"}  # 'soon' expired mid-window


def test_record_cache_all_expired_is_miss():
    """When EVERY cached record expires, the entry drops entirely — the
    next read re-resolves instead of serving an empty view for the rest
    of the TTL window."""
    from learning_at_home_tpu.dht import _RecordCache

    cache = _RecordCache(ttl=30.0)
    cache.put("k", {"a": (1, get_dht_time() + 0.1)})
    time.sleep(0.15)
    assert cache.get("k") is None
    assert cache.misses == 1


def test_record_cache_negative_caching_and_ttl():
    """An EMPTY lookup result is cached too (one lookup per window for a
    miss storm on a dead prefix), and ages out at the TTL like any entry."""
    from learning_at_home_tpu.dht import _RecordCache

    cache = _RecordCache(ttl=0.2)
    cache.put("missing", {})
    assert cache.get("missing") == {}  # negative hit: no lookup needed
    assert cache.hits == 1
    time.sleep(0.25)
    assert cache.get("missing") is None  # window over: re-resolve


def test_record_cache_invalidate_matches_wire_key():
    """Cache keys are the DHT wire form (DHTID digest): protocol
    ``on_store_observed`` only ever sees wire keys, so an inbound store
    must invalidate the entry cached under the PLAINTEXT key."""
    from learning_at_home_tpu.dht import _RecordCache

    cache = _RecordCache(ttl=30.0)
    cache.put("ffn", {"x": (1, get_dht_time() + 30)})
    cache.invalidate(DHTID.from_key("ffn").to_bytes())  # wire-form key
    assert cache.get("ffn") is None
    assert cache.invalidations == 1


def test_dht_cache_hit_serves_without_rpcs_and_bypass_forces_lookup():
    dht1 = DHT()
    dht2 = DHT(initial_peers=[dht1.endpoint])
    try:
        dht1.declare_experts_sync(
            ["ffn.0.0"], ("10.0.0.1", 9000), expiration=30
        )
        first = dht2.get_sync("ffn.0.0")
        assert "@10.0.0.1:9000" in first
        sent = sum(dht2.node.protocol.rpcs_sent.values())
        assert dht2.get_sync("ffn.0.0") == first
        assert sum(dht2.node.protocol.rpcs_sent.values()) == sent, (
            "second read within the TTL window must be served from cache"
        )
        assert dht2.get_sync("ffn.0.0", bypass_cache=True) == first
        assert sum(dht2.node.protocol.rpcs_sent.values()) > sent, (
            "bypass_cache must run a real iterative lookup"
        )
    finally:
        dht2.shutdown()
        dht1.shutdown()


def test_dht_cache_invalidated_by_own_store():
    """Read-your-writes: this handle's own declare invalidates its cached
    read, so the next read sees the new expert mid-TTL-window."""
    dht1 = DHT(cache_ttl=30.0)
    dht2 = DHT(initial_peers=[dht1.endpoint], cache_ttl=30.0)
    try:
        dht1.declare_experts_sync(
            ["ffn.0.0"], ("10.0.0.1", 9000), expiration=30
        )
        assert set(dht2._loop.run(dht2._get_alive("ffn"))) == {"ffn.0.0"}
        dht2.declare_experts_sync(
            ["ffn.0.1"], ("10.0.0.2", 9000), expiration=30
        )
        alive = dht2._loop.run(dht2._get_alive("ffn"))
        assert set(alive) == {"ffn.0.0", "ffn.0.1"}
    finally:
        dht2.shutdown()
        dht1.shutdown()


def test_dht_cache_invalidated_by_inbound_store():
    """In a 2-node swarm both nodes hold every record, so a declare on
    one lands an inbound store RPC on the other — whose cached read of
    the prefix must invalidate (``on_store_observed``), not serve the
    stale roster for the rest of a 30 s window."""
    dht1 = DHT(cache_ttl=30.0)
    dht2 = DHT(initial_peers=[dht1.endpoint], cache_ttl=30.0)
    try:
        dht1.declare_experts_sync(
            ["ffn.0.0"], ("10.0.0.1", 9000), expiration=30
        )
        assert set(dht2._loop.run(dht2._get_alive("ffn"))) == {"ffn.0.0"}
        dht1.declare_experts_sync(
            ["ffn.0.1"], ("10.0.0.1", 9001), expiration=30
        )
        alive = dht2._loop.run(dht2._get_alive("ffn"))
        assert set(alive) == {"ffn.0.0", "ffn.0.1"}, (
            "inbound store did not invalidate the cached prefix read"
        )
    finally:
        dht2.shutdown()
        dht1.shutdown()


# ---------------- batched multi-key store (ISSUE 11) ----------------


def test_store_many_wire_parity_with_per_key_stores():
    """The coalesced multi-key store bundle must land byte-for-byte the
    same records (values AND expirations) as the per-key path — while
    spending fewer store RPCs than one per (key, subkey)."""

    async def main():
        nodes = await make_swarm(6, bucket_size=4)
        try:
            exp = get_dht_time() + 30
            batched = [
                (f"bk.{i}", f"s{j}", [i, j], exp)
                for i in range(3)
                for j in range(2)
            ]
            sent0 = nodes[1].protocol.rpcs_sent.get("store", 0)
            acks = await nodes[1].store_many(batched)
            batched_rpcs = nodes[1].protocol.rpcs_sent.get("store", 0) - sent0
            assert all(acks), acks

            sent0 = nodes[2].protocol.rpcs_sent.get("store", 0)
            for i in range(3):
                for j in range(2):
                    ok = await nodes[2].store(
                        f"pk.{i}", [i, j], exp, subkey=f"s{j}"
                    )
                    assert ok
            per_key_rpcs = nodes[2].protocol.rpcs_sent.get("store", 0) - sent0

            for i in range(3):
                b = await nodes[5].get(f"bk.{i}")
                p = await nodes[5].get(f"pk.{i}")
                assert set(b) == set(p) == {"s0", "s1"}, (i, b, p)
                for j in range(2):
                    assert b[f"s{j}"][0] == p[f"s{j}"][0] == [i, j]
                    assert b[f"s{j}"][1] == p[f"s{j}"][1] == exp
            # 6 per-(key,subkey) calls each fan to ~k peers; the bundle
            # pays at most one store RPC per destination peer
            assert batched_rpcs <= len(nodes) < per_key_rpcs, (
                batched_rpcs, per_key_rpcs,
            )
        finally:
            await teardown(nodes)

    run(main())


def test_dead_peer_alive_refresh_bounded_by_adaptive_ceiling():
    """A cache-bypassing alive refresh with dead-but-not-yet-evicted DHT
    peers must finish within ~one adaptive-timeout ceiling: each dead
    contact costs at most ``rpc_timeout`` and a lookup wave contacts
    alpha peers in parallel, so dead-peer stalls do not stack."""
    dht1 = DHT(rpc_timeout=0.4)
    dead = [
        DHT(initial_peers=[dht1.endpoint], rpc_timeout=0.4) for _ in range(2)
    ]
    client = DHT(initial_peers=[dht1.endpoint], rpc_timeout=0.4)
    try:
        dht1.declare_experts_sync(
            ["ffn.0.0", "ffn.1.0"], ("10.0.0.1", 9000), expiration=30
        )
        client.get_sync("ffn")  # lookups learn the soon-dead peers
        for d in dead:
            d.shutdown()
        t0 = time.monotonic()
        alive = client._loop.run(
            client._get_alive("ffn", bypass_cache=True), timeout=30
        )
        elapsed = time.monotonic() - t0
        assert set(alive) == {"ffn.0.0", "ffn.1.0"}
        assert elapsed < 1.0, (
            f"fresh alive refresh stalled {elapsed:.2f}s — dead peers must "
            f"cost at most the adaptive ceiling (0.4s), paid in parallel"
        )
    finally:
        client.shutdown()
        dht1.shutdown()
