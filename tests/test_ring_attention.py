"""Ring attention vs dense reference on the 8-device virtual mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from learning_at_home_tpu.parallel.mesh import make_mesh
from learning_at_home_tpu.parallel.ring_attention import make_ring_attention

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices"
)


def dense_attention(q, k, v, causal):
    b, s, h, hd = q.shape
    scores = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(hd)
    if causal:
        mask = np.tril(np.ones((s, s), bool))
        scores = np.where(mask, scores, -np.inf)
    p = jax.nn.softmax(jnp.asarray(scores), axis=-1)
    return np.einsum("bhqk,bkhd->bqhd", np.asarray(p), v)


@pytest.mark.parametrize("causal", [True, False])
def test_ring_matches_dense(causal):
    mesh = make_mesh({"seq": 8})
    rs = np.random.RandomState(0)
    B, S, H, HD = 2, 64, 4, 8
    q = rs.randn(B, S, H, HD).astype(np.float32)
    k = rs.randn(B, S, H, HD).astype(np.float32)
    v = rs.randn(B, S, H, HD).astype(np.float32)
    ring = make_ring_attention(mesh, causal=causal)
    out = jax.jit(ring)(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    expected = dense_attention(q, k, v, causal)
    np.testing.assert_allclose(np.asarray(out), expected, atol=2e-5, rtol=2e-5)


def test_ring_attention_grads():
    mesh = make_mesh({"seq": 8})
    rs = np.random.RandomState(1)
    B, S, H, HD = 1, 32, 2, 4
    q = jnp.asarray(rs.randn(B, S, H, HD).astype(np.float32))
    k = jnp.asarray(rs.randn(B, S, H, HD).astype(np.float32))
    v = jnp.asarray(rs.randn(B, S, H, HD).astype(np.float32))
    ring = make_ring_attention(mesh, causal=True)

    def ring_loss(q, k, v):
        return (ring(q, k, v) ** 2).sum()

    def dense_loss(q, k, v):
        s = q.shape[1]
        scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(HD)
        scores = jnp.where(jnp.tril(jnp.ones((s, s), bool)), scores, -jnp.inf)
        p = jax.nn.softmax(scores, axis=-1)
        out = jnp.einsum("bhqk,bkhd->bqhd", p, v)
        return (out**2).sum()

    g_ring = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(q, k, v)
    g_dense = jax.jit(jax.grad(dense_loss, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_ring, g_dense):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-4, rtol=3e-4)


def test_seq_parallel_transformer_matches_dense():
    """Flagship with seq_parallel=True must equal the dense-attention model."""
    import optax

    from learning_at_home_tpu.models.transformer import (
        DMoETransformerConfig,
        DMoETransformerLM,
    )

    mesh_sp = make_mesh({"data": 2, "expert": 2, "seq": 2})
    mesh_plain = make_mesh({"data": 2, "expert": 4})
    base = dict(
        vocab_size=64, d_model=32, n_layers=1, n_heads=4, seq_len=16,
        num_experts=4, k=4, capacity_factor=8.0, dtype=jnp.float32,
    )
    m_sp = DMoETransformerLM(
        DMoETransformerConfig(**base, seq_parallel=True), mesh_sp
    )
    m_plain = DMoETransformerLM(DMoETransformerConfig(**base), mesh_plain)
    params = m_plain.init_params(jax.random.PRNGKey(0))
    params_host = jax.device_get(params)

    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, 64, (4, 16)))
    tgt = jnp.asarray(rs.randint(0, 64, (4, 16)))
    _, met_plain = jax.jit(m_plain.loss_fn)(params, ids, tgt)
    params_sp = jax.device_put(params_host, m_sp.param_shardings(params_host))
    _, met_sp = jax.jit(m_sp.loss_fn)(params_sp, ids, tgt)
    # compare CE: the aux load-balance term is a per-shard approximation and
    # legitimately varies with the shard partitioning
    np.testing.assert_allclose(
        float(met_sp["ce"]), float(met_plain["ce"]), rtol=1e-5
    )


def test_ring_long_sequence_memory_shape():
    """Sequence 8x longer than one shard still runs (the point of the ring)."""
    mesh = make_mesh({"seq": 8})
    ring = make_ring_attention(mesh, causal=True)
    B, S, H, HD = 1, 512, 2, 8
    rs = np.random.RandomState(2)
    q = jnp.asarray(rs.randn(B, S, H, HD).astype(np.float32))
    out = jax.jit(ring)(q, q, q)
    assert out.shape == (B, S, H, HD)
    assert np.isfinite(np.asarray(out)).all()


def test_ring_validation_errors():
    mesh = make_mesh({"seq": 8})
    ring = make_ring_attention(mesh)
    q = jnp.ones((1, 20, 2, 8))  # 20 % 8 != 0
    with pytest.raises(ValueError, match="divide"):
        ring(q, q, q)
    with pytest.raises(ValueError, match="no 'nope' axis"):
        make_ring_attention(mesh, axis_name="nope")
    # mismatched k/v shapes must fail loudly too, not deep inside shard_map
    q2 = jnp.ones((1, 40, 2, 8))
    k2 = jnp.ones((1, 24, 2, 8))
    with pytest.raises(ValueError, match="must match"):
        ring(q2, k2, k2)


@pytest.mark.parametrize("n_seq", [1, 2, 4, 8])
def test_zigzag_matches_dense(n_seq):
    """Zigzag layout: exact same math as dense causal attention, at every
    ring size (n=1..8 chunk-pair layouts hit all mask branches incl.
    the degenerate single-device diag-only ring)."""
    mesh = make_mesh({"seq": n_seq}, devices=jax.devices()[:n_seq])
    rs = np.random.RandomState(2)
    B, S, H, HD = 2, 16 * n_seq, 3, 8
    q = rs.randn(B, S, H, HD).astype(np.float32)
    k = rs.randn(B, S, H, HD).astype(np.float32)
    v = rs.randn(B, S, H, HD).astype(np.float32)
    ring = make_ring_attention(mesh, causal=True, layout="zigzag")
    out = jax.jit(ring)(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
    expected = dense_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), expected, atol=2e-5, rtol=2e-5)


def test_zigzag_matches_contiguous_and_grads():
    mesh = make_mesh({"seq": 4}, devices=jax.devices()[:4])
    rs = np.random.RandomState(3)
    B, S, H, HD = 1, 64, 2, 4
    q = jnp.asarray(rs.randn(B, S, H, HD).astype(np.float32))
    k = jnp.asarray(rs.randn(B, S, H, HD).astype(np.float32))
    v = jnp.asarray(rs.randn(B, S, H, HD).astype(np.float32))
    zig = make_ring_attention(mesh, causal=True, layout="zigzag")
    cont = make_ring_attention(mesh, causal=True)
    np.testing.assert_allclose(
        np.asarray(jax.jit(zig)(q, k, v)),
        np.asarray(jax.jit(cont)(q, k, v)),
        atol=2e-5, rtol=2e-5,
    )

    def loss(fn, q, k, v):
        return (fn(q, k, v) ** 2).sum()

    gz = jax.jit(jax.grad(lambda q: loss(zig, q, k, v)))(q)
    gc = jax.jit(jax.grad(lambda q: loss(cont, q, k, v)))(q)
    np.testing.assert_allclose(np.asarray(gz), np.asarray(gc), atol=5e-5, rtol=5e-5)


def test_zigzag_rejects_bad_config():
    mesh = make_mesh({"seq": 4}, devices=jax.devices()[:4])
    with pytest.raises(ValueError, match="CAUSAL"):
        make_ring_attention(mesh, causal=False, layout="zigzag")
    ring = make_ring_attention(mesh, causal=True, layout="zigzag")
    bad = jnp.zeros((1, 20, 2, 4))  # 20 not divisible by 2*4... by shards
    with pytest.raises(ValueError):
        jax.jit(ring)(bad, bad, bad)
