"""Paged KV cache + shared-prefix reuse + chunked prefill (ISSUE 13).

The contracts under test:

- **bitwise token parity**: the paged decoder (page-table gathers into
  the same masked-softmax core) emits token streams identical to the
  dense slot-table decoder on the same prompts against the same servers,
  for aligned and unaligned page sizes, and regardless of the prefill
  chunk size;
- **prefix cache**: a prompt whose prefix is already resident maps the
  shared pages read-only and skips prefill compute for those tokens,
  with output identical to a cold decode; the boundary page is served
  copy-on-write — the writer gets a private page and the shared source
  page provably never changes;
- **page pressure**: exhaustion sheds with a well-formed
  ``retry_after_s`` busy frame (0 error frames), preemption requeues
  recompute token-identical continuations, and a prompt that cannot
  EVER fit the pool errors at submit instead of wedging the queue;
- pure pool/refcount/reclaim bookkeeping (no swarm needed).
"""

import contextlib
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from learning_at_home_tpu.client import reset_client_rpc
from learning_at_home_tpu.client.routing import StaticExpertSource
from learning_at_home_tpu.gateway import Gateway, GatewayClient
from learning_at_home_tpu.models.kv_pages import PagedKVCache, PagePressure
from learning_at_home_tpu.models.swarm_decoder import SwarmKVDecoder
from learning_at_home_tpu.models.transformer_swarm import (
    SwarmDMoETransformerLM,
    SwarmTransformerConfig,
)
from learning_at_home_tpu.server.server import background_server

D = 16
VOCAB = 32
SEQ = 16
LAYERS = 2
UIDS = [f"ffn{layer}.{e}" for layer in range(LAYERS) for e in range(2)]


def _cfg(**overrides):
    base = dict(
        vocab_size=VOCAB, d_model=D, n_layers=LAYERS, n_heads=4,
        seq_len=SEQ, grid_size=(2,), k_best=2, k_min=2, uid_prefix="ffn",
        timeout_after_k_min=30.0,
        forward_timeout=60.0, backward_timeout=60.0,
        wire_codec="none", routing_cost_weight=0,
    )
    base.update(overrides)
    return SwarmTransformerConfig(**base)


@pytest.fixture()
def swarm():
    """One in-process server hosting all experts + a swarm model."""
    with contextlib.ExitStack() as stack:
        endpoint, _srv = stack.enter_context(
            background_server(expert_uids=UIDS, hidden_dim=D, seed=0)
        )
        src = StaticExpertSource({u: endpoint for u in UIDS})
        model = SwarmDMoETransformerLM(_cfg(), src)
        params = model.init_params(jax.random.PRNGKey(0))
        yield model, params
    reset_client_rpc()


# ---------------------------------------------------------------------------
# pure pool bookkeeping (no swarm)
# ---------------------------------------------------------------------------


def _pool(**overrides):
    kw = dict(
        n_layers=1, n_heads=2, head_dim=4, dtype=jnp.float32,
        max_slots=2, seq_len=12, page_len=4, num_pages=5,
    )
    kw.update(overrides)
    return PagedKVCache(**kw)


def test_pool_alloc_release_refcounts():
    kv = _pool()
    assert kv.pages_total() == 4 and kv.pages_used() == 0
    p0 = kv.alloc_slot_page(0)
    p1 = kv.alloc_slot_page(0)
    assert p0 != 0 and p1 != 0 and p0 != p1
    assert kv.pages_used() == 2
    assert list(kv.page_table[0, :2]) == [p0, p1]
    kv.release_slot(0)
    assert kv.pages_used() == 0
    assert kv.refcount[p0] == 0 and kv.refcount[p1] == 0
    # scratch page 0 is never handed out even under churn
    for _ in range(3):
        pids = [kv.alloc_slot_page(1) for _ in range(3)]
        assert 0 not in pids
        kv.release_slot(1)


def test_pool_exhaustion_raises_page_pressure():
    kv = _pool(num_pages=3)  # 2 usable
    kv.alloc_slot_page(0)
    kv.alloc_slot_page(0)
    with pytest.raises(PagePressure):
        kv.alloc_slot_page(1)
    assert kv.alloc_failures_total == 1
    kv.release_slot(0)
    assert kv.alloc_slot_page(1) != 0  # recovers after release


def test_prefix_register_lookup_and_leaf_reclaim():
    kv = _pool(num_pages=8, max_slots=3)
    # simulate a finished 8-token prefill in slot 0: two full pages
    for _ in range(2):
        kv.alloc_slot_page(0)
    prompt = [1, 2, 3, 4, 5, 6, 7, 8]
    assert kv.register_prefix(0, prompt) == 2
    # an identical prompt matches one full page (the cap at p-1 keeps
    # the final token's prefill, so the second page only part-matches)
    full, partial = kv.prefix_lookup(prompt)
    assert len(full) == 1 and partial is not None
    entry, r = partial
    assert r == 3 and entry.tokens == (5, 6, 7, 8)
    # a longer prompt sharing both pages matches both in the chain
    full, partial = kv.prefix_lookup(prompt + [9, 10])
    assert len(full) == 2 and partial is None
    # a diverging prompt stops the chain at the divergence
    full, partial = kv.prefix_lookup([1, 2, 3, 4, 9, 9, 9, 9, 9])
    assert len(full) == 1 and partial is None
    # release the writer: pages now held only by the cache → reclaimable,
    # and reclaim drops the LEAF (second page) before the parent
    kv.release_slot(0)
    assert kv.pages_reclaimable() == 2
    leaf_pid = next(
        e.page_id for e in kv._entries.values() if e.tokens == (5, 6, 7, 8)
    )
    assert kv.reclaim(1) == 1
    assert kv.refcount[leaf_pid] == 0
    assert kv.pages_reclaimed_total == 1
    full, _partial = kv.prefix_lookup(prompt + [9, 10])
    assert len(full) == 1  # parent survives, chain just shortens


def test_shared_page_write_guard():
    kv = _pool(num_pages=6)
    kv.alloc_slot_page(0)
    assert kv.register_prefix(0, [1, 2, 3, 4]) == 1
    pid = int(kv.page_table[0, 0])
    assert kv.refcount[pid] == 2  # slot + cache entry
    k = jnp.zeros((1, 2, 4))
    with pytest.raises(AssertionError, match="copy-on-write"):
        kv.write_tokens(0, np.array([pid]), np.array([0]), k, k)
    # scratch-page writes (dead decode rows) stay allowed
    kv.write_tokens(0, np.array([0]), np.array([0]), k, k)


# ---------------------------------------------------------------------------
# bitwise token parity: paged == dense, any page size, any chunk size
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("page_len", [4, 5, 16])
def test_paged_vs_dense_bitwise_token_parity(swarm, page_len):
    """Same prompts, same servers: the paged decoder's greedy tokens are
    IDENTICAL to the dense slot table's — the gather through the page
    table feeds the same masked-softmax core (trunk.py), so parity is
    bitwise, not approximate."""
    model, params = swarm
    prompts = [[1, 2, 3], [4, 5], [7, 8, 9, 10, 11]]
    dense = SwarmKVDecoder(model, params, max_slots=3)
    paged = SwarmKVDecoder(
        model, params, max_slots=3, kv_layout="paged", page_len=page_len
    )
    out_d = dense.generate(prompts, max_new_tokens=6)
    out_p = paged.generate(prompts, max_new_tokens=6)
    assert out_d == out_p
    # every page went back to the pool
    assert paged.kv.pages_used() - paged.kv.pages_reclaimable() <= 0


@pytest.mark.parametrize("chunk", [1, 3, 64])
def test_chunked_prefill_token_equal_any_chunk_size(swarm, chunk):
    """Prefill in chunks of 1, 3 or all-at-once: identical first token
    and identical decode continuation — chunking is a scheduling choice,
    never a numerics choice."""
    model, params = swarm
    prompt = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3]
    ref = SwarmKVDecoder(model, params, max_slots=1).generate(
        [prompt], max_new_tokens=4
    )[0]
    dec = SwarmKVDecoder(
        model, params, max_slots=1, kv_layout="paged", page_len=4,
        prefix_cache=False,
    )
    assert dec.begin_prefill(0, prompt, stream_id="s") == 0
    toks = []
    tok = None
    while tok is None:
        consumed, tok = dec.prefill_step(0, chunk)
        assert consumed <= chunk
    toks.append(tok)
    while len(toks) < 4:
        assert dec.ensure_decode_pages() == []
        toks.append(int(dec.decode_step()[0]))
    assert toks == ref


# ---------------------------------------------------------------------------
# prefix cache: hits skip prefill; boundary COW never aliases a writer
# ---------------------------------------------------------------------------


def test_prefix_hit_skips_prefill_tokens_and_matches_cold(swarm):
    model, params = swarm
    A = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8]  # 3 full pages @ page_len 4
    B = A[:10] + [7]  # shares 10 tokens, then diverges
    warm = SwarmKVDecoder(
        model, params, max_slots=3, kv_layout="paged", page_len=4
    )
    cold = SwarmKVDecoder(
        model, params, max_slots=1, kv_layout="paged", page_len=4,
        prefix_cache=False,
    )
    warm.prefill_into_slot(0, A, stream_id="a")
    skipped = warm.begin_prefill(1, B, stream_id="b")
    # 2 full shared pages + a 2-token COW boundary match
    assert skipped == 10
    assert warm.kv.prefix_hits_total == 1
    assert warm.kv.prefix_hit_tokens_total == 10
    assert warm.kv.cow_copies_total == 1
    consumed_total = 0
    tok = None
    while tok is None:
        consumed, tok = warm.prefill_step(1, SEQ)
        consumed_total += consumed
    # the hit SKIPPED compute: only the unmatched tail went through the
    # trunk/MoE
    assert consumed_total == len(B) - skipped == 1
    assert tok == cold.prefill_into_slot(0, B, stream_id="cold")
    # decode continuations stay identical too
    for _ in range(3):
        assert warm.ensure_decode_pages() == []
        assert cold.ensure_decode_pages() == []
        assert int(warm.decode_step()[1]) == int(cold.decode_step()[0])


def test_boundary_cow_never_aliases_writer(swarm):
    """The COW boundary page is a PRIVATE copy: the reader stream writes
    its divergent tail + decode tokens there while the shared source
    page stays bit-identical, and the original stream keeps decoding
    from unchanged pages."""
    model, params = swarm
    A = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3, 5, 8]
    B = A[:10] + [7]
    dec = SwarmKVDecoder(
        model, params, max_slots=2, kv_layout="paged", page_len=4
    )
    dec.prefill_into_slot(0, A, stream_id="a")
    src_pid = int(dec.kv.page_table[0, 2])  # A's third full page
    src_k = [np.asarray(kp[src_pid]) for kp in dec.kv.k_pools]
    src_v = [np.asarray(vp[src_pid]) for vp in dec.kv.v_pools]
    dec.begin_prefill(1, B, stream_id="b")
    cow_pid = int(dec.kv.page_table[1, 2])
    assert cow_pid != src_pid, "boundary page must be a private copy"
    assert int(dec.kv.refcount[cow_pid]) == 1
    tok = None
    while tok is None:
        _c, tok = dec.prefill_step(1, SEQ)
    # B decodes several tokens — all its writes land in private pages
    for _ in range(3):
        assert dec.ensure_decode_pages() == []
        dec.decode_step()
    for i in range(LAYERS):
        assert np.array_equal(np.asarray(dec.kv.k_pools[i][src_pid]),
                              src_k[i])
        assert np.array_equal(np.asarray(dec.kv.v_pools[i][src_pid]),
                              src_v[i])
    assert dec.kv.cow_copies_total == 1


# ---------------------------------------------------------------------------
# page pressure end-to-end: sheds carry retry_after_s, zero error frames
# ---------------------------------------------------------------------------


def test_page_exhaustion_sheds_with_retry_after_zero_errors(swarm):
    """While an occupant stream holds the whole 2-usable-page pool, a
    new stream's 2-page need exceeds headroom → a well-formed busy frame
    with ``retry_after_s`` — never an error frame — and service resumes
    once the occupant drains."""
    model, params = swarm
    with Gateway(
        model, params, max_slots=4, max_pending=64,
        page_len=8, num_pages=3,  # 2 usable pages — one stream's worth
        prefix_cache=False,
    ) as gw:
        client = GatewayClient(gw.endpoint)
        shed = None
        occupants = []
        for _attempt in range(5):
            sub = client.submit([1, 2, 3, 4], 12)
            if not sub.get("accepted"):
                shed = sub  # a previous occupant still holds the pool
                break
            occupants.append(sub["sid"])
            # wait until the occupant is live (headroom now < 2) …
            deadline = time.monotonic() + 60
            while time.monotonic() < deadline:
                if client.poll(sub["sid"], 0).get("tokens"):
                    break
                time.sleep(0.005)
            # … then probe; if the occupant drained first, go again
            probe = client.submit([5, 6, 7, 8], 8)
            if probe.get("shed"):
                shed = probe
                break
            occupants.append(probe["sid"])
        assert shed is not None, "pool under occupancy never shed"
        assert shed["accepted"] is False and shed["shed"] is True
        assert isinstance(shed["retry_after_s"], float)
        assert shed["retry_after_s"] > 0
        assert "page pressure" in shed["message"]
        assert gw.admission.shed_pages_total >= 1
        # every accepted stream completes cleanly — pressure sheds, it
        # never errors — and the drained pool serves new work again
        for sid in occupants:
            out = _poll_done(client, sid)
            assert out.get("error") is None, out
        out = client.generate([1, 2, 3, 4], 8)
        assert not out.get("shed") and not out.get("error")
        assert gw.scheduler.streams_errored_total == 0


def _poll_done(client, sid, deadline_s=60.0):
    deadline = time.monotonic() + deadline_s
    cursor = 0
    tokens = []
    while time.monotonic() < deadline:
        out = client.poll(sid, cursor)
        tokens.extend(out.get("tokens") or [])
        cursor = int(out.get("cursor") or cursor)
        if out.get("done"):
            out["tokens"] = tokens
            return out
        time.sleep(0.01)
    raise AssertionError(f"stream {sid} never finished")


def test_preemption_recompute_is_token_identical(swarm):
    """A pool too small for every admitted stream's full depth forces
    preemption; the victim is requeued with prompt+tokens and greedy
    determinism makes its final stream IDENTICAL to an uncontended
    run."""
    model, params = swarm
    prompts = [[1, 2], [9, 8]]
    n_new = SEQ - 2
    ref = {}
    for p in prompts:
        ref[tuple(p)] = SwarmKVDecoder(model, params, max_slots=1).generate(
            [p], max_new_tokens=n_new
        )[0]
    with Gateway(
        model, params, max_slots=2, max_pending=64,
        page_len=2, num_pages=10,  # 9 usable < 2 streams × 8 pages
        prefix_cache=False, prefill_chunk_tokens=4,
    ) as gw:
        client = GatewayClient(gw.endpoint)
        # enqueue directly on the scheduler (admission would serialise
        # them and hide the contention this test exists to create)
        sids = [gw.scheduler.submit(p, n_new) for p in prompts]
        for p, sid in zip(prompts, sids):
            out = _poll_done(client, sid)
            assert out.get("error") is None, out
            assert out["tokens"] == ref[tuple(p)]
        assert gw.scheduler.preemptions_total >= 1, (
            "9 usable pages cannot hold two 8-page streams — a "
            "preemption must have happened"
        )
        assert gw.scheduler.streams_errored_total == 0


def test_prompt_that_can_never_fit_pool_errors_cleanly(swarm):
    """A prompt within seq_len but larger than the WHOLE pool must be an
    error frame at submit — requeueing it would wedge admission
    forever."""
    model, params = swarm
    from learning_at_home_tpu.utils.connection import RemoteCallError

    with Gateway(
        model, params, max_slots=2, page_len=2, num_pages=4,  # 3 usable
    ) as gw:
        client = GatewayClient(gw.endpoint)
        with pytest.raises(RemoteCallError):
            client.submit(list(range(1, 11)), 4)  # needs 5-6 pages
        # small prompts still serve
        out = client.generate([1, 2, 3], 2)
        assert not out.get("error") and len(out["tokens"]) == 2
        assert gw.scheduler.streams_errored_total == 0


# ---------------------------------------------------------------------------
# gateway end-to-end: chunked prefill + paged default serve correctly
# ---------------------------------------------------------------------------


def test_gateway_chunked_prefill_tokens_match_serial(swarm):
    """The same workload through chunked-prefill and serial-prefill
    gateways produces identical per-stream tokens — interleaving is a
    latency policy, not an output policy."""
    model, params = swarm
    prompts = [[1, 2, 3], [4, 5, 6, 7, 8, 9, 10, 11, 12], [7, 8]]
    results = {}
    for label, chunk in (("chunked", 4), ("serial", 0)):
        with Gateway(
            model, params, max_slots=4, prefill_chunk_tokens=chunk
        ) as gw:
            client = GatewayClient(gw.endpoint)
            outs = [client.generate(p, 4) for p in prompts]
            assert all(
                not o.get("shed") and not o.get("error") for o in outs
            )
            results[label] = [o["tokens"] for o in outs]
            if chunk:
                assert gw.decoder.prefill_chunks_total >= 3
    assert results["chunked"] == results["serial"]
