"""Merged per-peer fan-out: the ``multi`` RPC and its quorum semantics.

One request serves every expert a client picked on a peer; per-part
failures are per-part, a lying reply fails the whole group, and the
quorum loop disaggregates a failed merged call into per-expert singles
so intra-peer redundancy survives transient whole-request drops.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from learning_at_home_tpu.client import reset_client_rpc
from learning_at_home_tpu.client.moe import RemoteMixtureOfExperts
from learning_at_home_tpu.client.routing import StaticExpertSource
from learning_at_home_tpu.client.rpc import client_loop, pool_registry
from learning_at_home_tpu.server import background_server
from learning_at_home_tpu.server.chaos import ChaosConfig

HID = 16


def _rpc(endpoint, msg_type, tensors, meta):
    async def call():
        return await pool_registry().get(endpoint).rpc(
            msg_type, tensors, meta, timeout=10.0
        )

    return client_loop().run(call())


def test_multi_forward_parts_and_partial_failure():
    with background_server(
        num_experts=3, hidden_dim=HID, expert_prefix="ffn", seed=3
    ) as (endpoint, srv):
        rs = np.random.RandomState(0)
        xa = rs.randn(4, HID).astype(np.float32)
        xb = rs.randn(2, HID).astype(np.float32)
        tensors, meta = _rpc(
            endpoint,
            "multi",
            [xa, xb, xa],
            {"op": "forward", "parts": [
                {"uid": "ffn.0", "n_tensors": 1},
                {"uid": "ffn.1", "n_tensors": 1},
                {"uid": "ffn.nope", "n_tensors": 1},  # unknown: per-part fail
            ]},
        )
        parts = meta["parts"]
        assert [p["uid"] for p in parts] == ["ffn.0", "ffn.1", "ffn.nope"]
        assert parts[0]["ok"] and parts[1]["ok"] and not parts[2]["ok"]
        assert "unknown expert" in parts[2]["message"]
        assert len(tensors) == 2  # only successful parts ship outputs
        # replies match what the single-expert RPC produces
        single_a, _ = _rpc(endpoint, "forward", [xa], {"uid": "ffn.0"})
        np.testing.assert_allclose(tensors[0], single_a[0], atol=1e-6)
        assert tensors[1].shape == (2, HID)
    reset_client_rpc()


def test_multi_malformed_meta_rejected():
    with background_server(
        num_experts=1, hidden_dim=HID, expert_prefix="ffn", seed=3
    ) as (endpoint, srv):
        from learning_at_home_tpu.utils.connection import RemoteCallError

        x = np.zeros((2, HID), np.float32)
        with pytest.raises(RemoteCallError, match="inconsistent|parts"):
            _rpc(endpoint, "multi", [x], {"op": "forward", "parts": [
                {"uid": "ffn.0", "n_tensors": 7}  # claims more than shipped
            ]})
        with pytest.raises(RemoteCallError, match="op forward|backward"):
            _rpc(endpoint, "multi", [x], {"op": "info", "parts": []})
    reset_client_rpc()


def test_moe_merge_matches_per_expert_fanout():
    """Numerics are identical whichever wire strategy carries the rows."""
    with background_server(
        num_experts=8, hidden_dim=HID, expert_prefix="ffn", seed=4
    ) as (endpoint, srv):
        x = jnp.asarray(np.random.RandomState(1).randn(5, HID).astype(np.float32))
        outs = []
        for merge in (True, False):
            source = StaticExpertSource({uid: endpoint for uid in srv.experts})
            moe = RemoteMixtureOfExperts(
                in_features=HID, grid_size=(8,), uid_prefix="ffn",
                source=source, k_best=4, k_min=1, merge_rpcs=merge,
            )
            gate = moe.init_gate_params(jax.random.PRNGKey(0))
            outs.append(np.asarray(moe(x, gate)))
        np.testing.assert_allclose(outs[0], outs[1], atol=1e-5)
    reset_client_rpc()


def test_merged_drop_disaggregates_to_singles():
    """A dropped merged reply must not kill intra-peer redundancy: the
    quorum loop retries the peer's experts as independent singles."""
    chaos = ChaosConfig(drop_prob=0.45, seed=11)
    with background_server(
        num_experts=4, hidden_dim=HID, expert_prefix="ffn", seed=6, chaos=chaos
    ) as (endpoint, srv):
        source = StaticExpertSource({uid: endpoint for uid in srv.experts})
        moe = RemoteMixtureOfExperts(
            in_features=HID, grid_size=(4,), uid_prefix="ffn", source=source,
            k_best=4, k_min=1, timeout_after_k_min=0.1, forward_timeout=1.5,
        )
        gate = moe.init_gate_params(jax.random.PRNGKey(0))
        x = jnp.asarray(np.random.RandomState(2).randn(3, HID).astype(np.float32))
        from learning_at_home_tpu.client.moe import MoEDispatchError

        # each call sends ONE merged request (drop prob 0.45) — loop until a
        # call BOTH saw a drop and still returned finite output, proving the
        # disaggregation retry carried it.  (A call where the merged frame
        # AND all four single retries drop — p≈2% — legitimately raises;
        # tolerate it and keep going.)
        survived_with_drop = False
        for _ in range(12):
            drops_before = srv.chaos.injected_drops
            try:
                out = np.asarray(moe(x, gate))
            except MoEDispatchError:
                continue
            assert np.isfinite(out).all()
            if srv.chaos.injected_drops > drops_before:
                survived_with_drop = True
                break
        assert survived_with_drop
    reset_client_rpc()
