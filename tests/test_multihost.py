"""Execution coverage for the multi-host (DCN tier-a) bring-up path.

Round-2 verdict missing #2: ``initialize_multihost`` had zero execution
coverage.  This launches TWO real processes on localhost — a coordinator
and a worker — each with 2 virtual CPU devices, and drives the full
bring-up: ``jax.distributed.initialize`` via ``initialize_multihost``,
``host_local_array_to_global`` batch assembly, one psum'd ``shard_map``
step over both processes, and a ``ShardedMixtureOfExperts`` forward whose
``all_to_all`` crosses the process boundary (the same program a pod slice
runs over ICI).
"""

import os
import socket
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WORKER = os.path.join(REPO, "tests", "multihost_worker.py")


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def test_two_process_distributed_bringup():
    from learning_at_home_tpu.utils.subproc import clean_jax_subprocess_env

    env = clean_jax_subprocess_env(repo_root=REPO)
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    addr = f"127.0.0.1:{_free_port()}"
    nproc = 2
    procs = [
        subprocess.Popen(
            [sys.executable, WORKER, str(pid), str(nproc), addr],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, cwd=REPO, env=env,
        )
        for pid in range(nproc)
    ]
    outs = []
    try:
        for p in procs:
            out, err = p.communicate(timeout=280)
            outs.append((p.returncode, out, err))
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    if any(rc == 3 for rc, _, _ in outs):
        reason = next(
            line for rc, out, _ in outs if rc == 3
            for line in out.splitlines() if line.startswith("MULTIHOST_SKIP")
        )
        pytest.skip(f"jax.distributed unsupported here: {reason}")
    for pid, (rc, out, err) in enumerate(outs):
        assert rc == 0, f"worker {pid} rc={rc}\nstdout: {out}\nstderr: {err[-2000:]}"
        assert f"MULTIHOST_OK pid={pid} devices=4" in out, out
    # both processes computed the SAME global MoE output (replicated norm)
    norms = {
        line.split("moe_norm=")[1]
        for _, out, _ in outs
        for line in out.splitlines() if "MULTIHOST_OK" in line
    }
    assert len(norms) == 1, norms
