"""Pallas dispatch kernel vs the reference gather (interpret mode on CPU;
the same kernel compiles natively on TPU)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from learning_at_home_tpu.ops import (
    dispatch_tokens_indexed,
    top_k_gating_indices,
)
from learning_at_home_tpu.ops.pallas_dispatch import dispatch_tokens_pallas


@pytest.mark.parametrize("n,E,k,cap", [(32, 8, 2, 6), (16, 4, 1, 2), (64, 16, 4, 8)])
def test_pallas_dispatch_matches_reference(n, E, k, cap):
    rs = np.random.RandomState(n + E)
    x = jnp.asarray(rs.randn(n, 128).astype(np.float32))
    logits = jnp.asarray(rs.randn(n, E).astype(np.float32))
    plan = top_k_gating_indices(logits, k=k, capacity=cap)
    ref = dispatch_tokens_indexed(x, plan)
    out = dispatch_tokens_pallas(x, plan, interpret=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-6)
    # empty slots are zero rows
    empty = np.asarray(plan.token_for_slot) < 0
    assert (np.asarray(out)[empty] == 0).all()


def test_pallas_dispatch_rejects_unaligned_d():
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(8, 100).astype(np.float32))  # 100 % 128 != 0
    plan = top_k_gating_indices(jnp.asarray(rs.randn(8, 4).astype(np.float32)), 1, 4)
    with pytest.raises(ValueError, match="128"):
        dispatch_tokens_pallas(x, plan, interpret=True)


def test_dispatch_tokens_auto_fallback():
    from learning_at_home_tpu.ops.pallas_dispatch import dispatch_tokens_auto

    rs = np.random.RandomState(1)
    # unaligned d: auto must fall back to the XLA gather, not raise
    x = jnp.asarray(rs.randn(8, 100).astype(np.float32))
    plan = top_k_gating_indices(
        jnp.asarray(rs.randn(8, 4).astype(np.float32)), 1, 4
    )
    out = dispatch_tokens_auto(x, plan, use_pallas=True)
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(dispatch_tokens_indexed(x, plan)), atol=1e-6
    )
    # aligned d with pallas requested: uses the kernel (interpret on CPU)
    x2 = jnp.asarray(rs.randn(8, 128).astype(np.float32))
    plan2 = top_k_gating_indices(
        jnp.asarray(rs.randn(8, 4).astype(np.float32)), 1, 4
    )
    out2 = dispatch_tokens_auto(x2, plan2, use_pallas=True, interpret=True)
    np.testing.assert_allclose(
        np.asarray(out2), np.asarray(dispatch_tokens_indexed(x2, plan2)), atol=1e-6
    )
