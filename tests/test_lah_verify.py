"""Self-tests for lah-verify (ISSUE 14): the interleaving explorer must
RE-FIND both mechanically re-introduced PR-13 scheduler races — the same
way a linter must fire on its bad corpus — and must explore the merged
tree clean.  Plus the sanitizer's quiesce-point resource audits: leaks
at claimed-idle moments are findings, drained here via
``expect_violations`` so the conftest guard stays green."""

import os
import subprocess
import sys
import time

import pytest

from learning_at_home_tpu.analysis import verify
from learning_at_home_tpu.utils import sanitizer

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

SMOKE_BUDGET = 40  # schedules per world: seconds, not minutes, in CI


# ---------------------------------------------------------------------------
# explorer: merged tree clean, seeded bugs re-found, determinism
# ---------------------------------------------------------------------------


def test_merged_tree_explores_clean():
    r = verify.explore_gateway(max_schedules=SMOKE_BUDGET)
    assert r.clean, "\n".join(str(v) for v in r.violations)
    assert r.schedules_run > 1, "explorer degenerated to a single schedule"


def test_merged_tree_clean_with_cancel_and_prefix_cache():
    for kw in ({"with_cancel": True}, {"prefix_cache": True}):
        r = verify.explore_gateway(max_schedules=SMOKE_BUDGET, **kw)
        assert r.clean, "\n".join(str(v) for v in r.violations)


def test_seeded_stale_prefill_is_refound():
    r = verify.explore_gateway(
        max_schedules=200, seeded_bug="stale-prefill"
    )
    assert r.violations, (
        "the PR-13 stale-snapshot revert was NOT re-found — the checker "
        "regressed"
    )


def test_seeded_mutual_preemption_is_refound():
    r = verify.explore_gateway(
        max_schedules=200, seeded_bug="mutual-preemption"
    )
    assert r.violations, (
        "the PR-13 exclude-the-raiser livelock revert was NOT re-found "
        "— the checker regressed"
    )
    details = " ".join(v.detail for v in r.violations)
    assert "preempt" in details or "stuck" in details


def test_seeded_bug_detection_is_deterministic():
    """Same seed => same first failing interleaving, twice over."""
    a = verify.explore_gateway(max_schedules=200,
                               seeded_bug="stale-prefill", seed=3)
    b = verify.explore_gateway(max_schedules=200,
                               seeded_bug="stale-prefill", seed=3)
    assert a.violations and b.violations
    assert a.violations[0].trace == b.violations[0].trace
    assert a.violations[0].schedule_index == b.violations[0].schedule_index


def test_unknown_seeded_bug_rejected():
    with pytest.raises(ValueError):
        verify._GatewayWorld(seeded_bug="nonsense").close()


def test_lifecycle_and_receiver_worlds_clean():
    r = verify.explore_lifecycle(max_schedules=SMOKE_BUDGET)
    assert r.clean, "\n".join(str(v) for v in r.violations)
    rr = verify.check_handoff_receiver()
    assert rr.clean, "\n".join(str(v) for v in rr.violations)


def test_collect_invariants_covers_every_module():
    rows = verify.collect_invariants()
    names = {n for n, _, _ in rows}
    prefixes = {n.split(".", 1)[0] for n in names}
    assert {"scheduler", "kv", "lifecycle"} <= prefixes
    assert len(rows) == len(names), "duplicate invariant names"


def test_virtual_clock_restored_after_exploration():
    from learning_at_home_tpu.gateway import scheduler as sched_mod

    before = sched_mod._monotonic
    verify.explore_gateway(max_schedules=2)
    assert sched_mod._monotonic is before
    # and the seam really is the process clock again
    assert abs(sched_mod._monotonic() - time.monotonic()) < 5.0


def test_smoke_cli_under_a_minute():
    t0 = time.monotonic()
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "lah_verify.py"),
         "--smoke"],
        capture_output=True, text=True, timeout=120,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    elapsed = time.monotonic() - t0
    assert r.returncode == 0, r.stdout + r.stderr
    assert "0 violation(s)" in r.stdout
    assert "stale-prefill FOUND" in r.stdout
    assert "mutual-preemption FOUND" in r.stdout
    assert elapsed < 60, f"smoke took {elapsed:.1f}s — too slow for CI"


def test_cli_list_invariants():
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "lah_verify.py"),
         "--list-invariants"],
        capture_output=True, text=True, timeout=60,
    )
    assert r.returncode == 0
    assert "scheduler.slot_unique" in r.stdout
    assert "lifecycle.drain_no_abort" in r.stdout


# ---------------------------------------------------------------------------
# quiesce-point resource audits (sanitizer side)
# ---------------------------------------------------------------------------


@pytest.mark.skipif(not sanitizer.enabled(), reason="needs LAH_SANITIZE=1")
def test_quiesce_point_reports_registered_leaks():
    leaks_to_report = ["widget left open"]
    sanitizer.register_quiesce_audit(
        "test.quiesce.demo", lambda: list(leaks_to_report)
    )
    try:
        with sanitizer.expect_violations("test.quiesce.demo") as seen:
            found = sanitizer.quiesce_point("test.quiesce.")
        assert found == ["test.quiesce.demo: widget left open"]
        assert [v["kind"] for v in seen] == ["quiesce"]
        # clean audits stay silent
        leaks_to_report.clear()
        assert sanitizer.quiesce_point("test.quiesce.") == []
    finally:
        sanitizer.unregister_quiesce_audit("test.quiesce.demo")


@pytest.mark.skipif(not sanitizer.enabled(), reason="needs LAH_SANITIZE=1")
def test_quiesce_point_audit_exception_is_a_finding():
    def broken():
        raise RuntimeError("audit infra died")

    sanitizer.register_quiesce_audit("test.quiesce.broken", broken)
    try:
        with sanitizer.expect_violations("test.quiesce.broken") as seen:
            found = sanitizer.quiesce_point("test.quiesce.broken")
        assert found and "audit raised RuntimeError" in found[0]
        assert seen
    finally:
        sanitizer.unregister_quiesce_audit("test.quiesce.broken")


@pytest.mark.skipif(not sanitizer.enabled(), reason="needs LAH_SANITIZE=1")
def test_scheduler_quiesce_audit_trips_on_leaked_slot():
    """A decoder slot held while the stream table is empty is exactly
    the leak the scheduler's claimed-idle audit exists to catch."""
    from learning_at_home_tpu.gateway.scheduler import SlotScheduler

    dec = verify._FakePagedDecoder()
    sched = SlotScheduler(dec, idle_wait_s=0.0, stream_ttl_s=1000.0,
                          prefill_chunk_tokens=2)
    # leak: the decoder claims a slot the scheduler never learns about
    dec.begin_prefill(0, [1, 2, 3], stream_id="ghost")
    with sanitizer.expect_violations("gateway.scheduler.") as seen:
        found = sanitizer.quiesce_point(sched._quiesce_site)
    assert found, "leaked slot not reported at the quiesce point"
    assert any("quiesce_baseline" in v["detail"] for v in seen)
    dec.evict(0)
    assert sanitizer.quiesce_point(sched._quiesce_site) == []


@pytest.mark.skipif(not sanitizer.enabled(), reason="needs LAH_SANITIZE=1")
def test_scheduler_quiesce_audit_silent_while_busy():
    """Mid-work states are NOT leaks — the audit only bites at idle."""
    from learning_at_home_tpu.gateway.scheduler import SlotScheduler

    dec = verify._FakePagedDecoder()
    sched = SlotScheduler(dec, idle_wait_s=0.0, stream_ttl_s=1000.0,
                          prefill_chunk_tokens=2)
    sched.submit([1, 2, 3], 2)  # pending, never scheduled
    assert sanitizer.quiesce_point(sched._quiesce_site) == []


def test_kv_audit_trips_on_seeded_refcount_corruption():
    dec = verify._FakePagedDecoder()
    assert dec.kv.audit() == []
    dec.kv.refcount[1] = 5  # nobody maps page 1
    leaks = dec.kv.audit()
    assert leaks, "seeded refcount corruption not detected"
    assert any("refcount" in leak for leak in leaks)
