"""Tests for the cross-request batcher + single-consumer device loop (M0)."""

import asyncio

import numpy as np
import pytest

from learning_at_home_tpu.server import Runtime, TaskPool, bucket_rows


def test_bucket_rows():
    assert bucket_rows(1, 64) == 1
    assert bucket_rows(2, 64) == 2
    assert bucket_rows(3, 64) == 4
    assert bucket_rows(33, 64) == 64
    assert bucket_rows(64, 64) == 64
    assert bucket_rows(100, 64) == 64  # clamped


def run_pool(coro):
    return asyncio.run(coro)


def test_single_task_roundtrip():
    async def main():
        calls = []

        def process(inputs):
            calls.append(tuple(a.shape for a in inputs))
            return [inputs[0] * 2]

        pool = TaskPool(process, "p", max_batch_size=8, batch_timeout=0.001)
        runtime = Runtime()
        runtime.attach_loop(asyncio.get_running_loop())
        runtime.start()
        pool.start(runtime)
        x = np.arange(6, dtype=np.float32).reshape(3, 2)
        (out,) = await pool.submit_task(x)
        runtime.shutdown()
        np.testing.assert_array_equal(out, x * 2)
        # 3 rows → padded to bucket 4
        assert calls == [((4, 2),)]
        assert pool.padded_rows == 1 and pool.total_rows == 3

    run_pool(main())


def test_cross_request_batching():
    """Concurrent tasks coalesce into one padded device batch."""

    async def main():
        batch_rows = []

        def process(inputs):
            batch_rows.append(inputs[0].shape[0])
            return [inputs[0] + 1]

        pool = TaskPool(process, "p", max_batch_size=64, batch_timeout=0.05)
        runtime = Runtime()
        runtime.attach_loop(asyncio.get_running_loop())
        runtime.start()
        pool.start(runtime)

        xs = [np.full((3, 2), i, np.float32) for i in range(5)]
        outs = await asyncio.gather(*(pool.submit_task(x) for x in xs))
        runtime.shutdown()
        for i, (out,) in enumerate(outs):
            np.testing.assert_array_equal(out, xs[i] + 1)
        # 5 tasks × 3 rows = 15 → one batch bucketed to 16
        assert batch_rows == [16]
        assert pool.batches_formed == 1

    run_pool(main())


def test_oversized_task_rejected():
    async def main():
        pool = TaskPool(lambda i: [i[0]], "p", max_batch_size=4)
        with pytest.raises(ValueError):
            await pool.submit_task(np.zeros((5, 1), np.float32))

    run_pool(main())


def test_error_propagates_to_futures():
    async def main():
        def process(inputs):
            raise RuntimeError("device on fire")

        pool = TaskPool(process, "p", max_batch_size=4, batch_timeout=0.001)
        runtime = Runtime()
        runtime.attach_loop(asyncio.get_running_loop())
        runtime.start()
        pool.start(runtime)
        with pytest.raises(RuntimeError, match="device on fire"):
            await pool.submit_task(np.zeros((1, 1), np.float32))
        runtime.shutdown()

    run_pool(main())


def test_priority_oldest_first():
    """Runtime drains jobs oldest-submission-first across pools."""

    async def main():
        order = []

        def mk(name):
            def process(inputs):
                order.append(name)
                return [inputs[0]]

            return process

        pool_a = TaskPool(mk("a"), "a", max_batch_size=2, batch_timeout=0.0)
        pool_b = TaskPool(mk("b"), "b", max_batch_size=2, batch_timeout=0.0)
        runtime = Runtime()
        runtime.attach_loop(asyncio.get_running_loop())
        # don't start the runtime yet: let both pools enqueue first
        fut_a = asyncio.ensure_future(pool_a.submit_task(np.zeros((1, 1), np.float32)))
        await asyncio.sleep(0.01)
        fut_b = asyncio.ensure_future(pool_b.submit_task(np.zeros((1, 1), np.float32)))
        await asyncio.sleep(0.01)
        pool_a.start(runtime)
        pool_b.start(runtime)
        await asyncio.sleep(0.05)  # managers form both jobs into the queue
        runtime.start()
        await asyncio.gather(fut_a, fut_b)
        runtime.shutdown()
        assert order == ["a", "b"]  # a arrived first

    run_pool(main())


def test_batcher_property_randomized():
    """Property test (SURVEY §5.2): under random task sizes, arrival jitter,
    and pool parameters, every task gets EXACTLY its own rows back —
    batching must never mix, drop, or reorder rows within a task."""
    import random

    rng = random.Random(0)
    for trial in range(5):
        max_bs = rng.choice([4, 8, 32])
        timeout = rng.choice([0.0, 0.001, 0.01])

        async def main():
            def process(inputs):
                # tag rows so misrouting is detectable: f(x) = x * 2 + 1
                return [inputs[0] * 2 + 1]

            pool = TaskPool(
                process, "prop", max_batch_size=max_bs, batch_timeout=timeout
            )
            runtime = Runtime()
            runtime.attach_loop(asyncio.get_running_loop())
            runtime.start()
            pool.start(runtime)

            async def one_task(i):
                n = rng.randint(1, max_bs)
                # unique payload per task: task id in col 0, row id in col 1
                x = np.stack(
                    [np.full(n, i, np.float32), np.arange(n, dtype=np.float32)],
                    axis=1,
                )
                if rng.random() < 0.5:
                    await asyncio.sleep(rng.random() * 0.01)
                (out,) = await pool.submit_task(x)
                np.testing.assert_array_equal(out, x * 2 + 1)

            await asyncio.gather(*(one_task(i) for i in range(40)))
            runtime.shutdown()

        run_pool(main())


def test_no_stacking_on_event_loop():
    """Regression for the pipelined hot path: per-batch stacking must not
    run on the asyncio loop thread.  The old version monkeypatched
    ``np.concatenate`` to track threads; now the sanitizer's first-class
    ``@runs_on("runtime")`` assertion on ``BatchJob.stack`` carries the
    invariant — the shared conftest guard fails this test on any
    violation, and the site stats prove the Runtime thread really did
    the stacking."""
    from learning_at_home_tpu.utils import sanitizer

    if not sanitizer.enabled():
        pytest.skip("sanitizer disabled (LAH_SANITIZE=0)")
    before = sanitizer.site_stats().get("BatchJob.stack", {})

    async def main():
        def process(inputs):
            return [inputs[0] * 2]

        pool = TaskPool(process, "p", max_batch_size=64, batch_timeout=0.01)
        runtime = Runtime()
        runtime.attach_loop(asyncio.get_running_loop())
        runtime.start()
        pool.start(runtime)
        xs = [np.full((3, 2), i, np.float32) for i in range(4)]
        outs = await asyncio.gather(*(pool.submit_task(x) for x in xs))
        runtime.shutdown()
        for i, (out,) in enumerate(outs):
            np.testing.assert_array_equal(out, xs[i] * 2)
        # the copies really happened runtime-side, into staging buffers
        assert runtime.stack_time >= 0.0
        assert runtime.staging.allocated >= 1

    run_pool(main())
    after = sanitizer.site_stats().get("BatchJob.stack", {})
    ran_on_runtime = after.get("runtime", 0) - before.get("runtime", 0)
    assert ran_on_runtime > 0, (
        f"BatchJob.stack never ran on the lah-runtime thread: {after}"
    )
    # loop-thread stacking would ALSO have tripped the conftest guard;
    # assert the observable here too so the failure reads locally
    for cls in after:
        if cls != "runtime" and after.get(cls, 0) > before.get(cls, 0):
            raise AssertionError(
                f"BatchJob.stack ran on a {cls!r} thread during this test"
            )


def test_staging_buffer_reuse_and_isolation():
    """Two in-flight batches of the same bucket must not share a staging
    buffer; once both retire, a later batch reuses one of them."""

    async def main():
        seen = {"a": [], "b": [], "c": []}

        def mk(name):
            def process(inputs):
                seen[name].append(id(inputs[0]))
                return [inputs[0] + 1]

            return process

        # distinct pools (distinct serial keys) with identical bucket
        # shapes: the runtime dispatches b while a is still in flight
        pool_a = TaskPool(mk("a"), "a", max_batch_size=8, batch_timeout=0.0)
        pool_b = TaskPool(mk("b"), "b", max_batch_size=8, batch_timeout=0.0)
        runtime = Runtime()
        runtime.attach_loop(asyncio.get_running_loop())
        # two tasks per pool so each batch stacks (multi-task → staging)
        xs = [np.full((1, 2), i, np.float32) for i in range(2)]
        futs = [
            asyncio.ensure_future(pool.submit_task(x))
            for pool in (pool_a, pool_b)
            for x in xs
        ]
        await asyncio.sleep(0.01)
        pool_a.start(runtime)
        pool_b.start(runtime)
        await asyncio.sleep(0.05)  # both jobs formed and queued
        runtime.start()
        await asyncio.gather(*futs)
        assert seen["a"] and seen["b"]
        # in-flight together → disjoint buffers
        assert not (set(seen["a"]) & set(seen["b"]))

        # a third batch, after both retired, reuses a pooled buffer
        pool_c = TaskPool(mk("c"), "c", max_batch_size=8, batch_timeout=0.0)
        pool_c.start(runtime)
        reused_before = runtime.staging.reused
        await asyncio.gather(*(pool_c.submit_task(x) for x in xs))
        runtime.shutdown()
        assert runtime.staging.reused > reused_before
        assert set(seen["c"]) <= (set(seen["a"]) | set(seen["b"]))

    run_pool(main())


class _LazyArray:
    """Output whose materialization (np.asarray) is observable — stands in
    for an async XLA array that blocks when fetched."""

    def __init__(self, value, events, tag):
        self.value = value
        self.events = events
        self.tag = tag

    def __array__(self, dtype=None):
        self.events.append(("materialize", self.tag))
        return np.asarray(self.value, dtype)


def test_double_buffering_overlap_and_serialization():
    """Different serial keys: job B dispatches BEFORE job A materializes
    (the overlap).  Same serial key: A must fully materialize before B
    dispatches (per-expert update serialization)."""

    def run_pair(key_a, key_b):
        events = []

        async def main():
            def mk(tag):
                def process(inputs):
                    events.append(("dispatch", tag))
                    return [_LazyArray(inputs[0], events, tag)]

                return process

            pool_a = TaskPool(mk("A"), "pa", max_batch_size=4,
                              batch_timeout=0.0, serial_key=key_a)
            pool_b = TaskPool(mk("B"), "pb", max_batch_size=4,
                              batch_timeout=0.0, serial_key=key_b)
            runtime = Runtime()
            runtime.attach_loop(asyncio.get_running_loop())
            x = np.ones((1, 2), np.float32)
            fut_a = asyncio.ensure_future(pool_a.submit_task(x))
            await asyncio.sleep(0.01)
            fut_b = asyncio.ensure_future(pool_b.submit_task(x))
            await asyncio.sleep(0.01)
            pool_a.start(runtime)
            pool_b.start(runtime)
            await asyncio.sleep(0.05)  # both jobs queued before the loop runs
            runtime.start()
            await asyncio.gather(fut_a, fut_b)
            runtime.shutdown()
            return runtime

        runtime = run_pool(main())
        return events, runtime

    events, runtime = run_pair("k1", "k2")
    assert events == [
        ("dispatch", "A"), ("dispatch", "B"),
        ("materialize", "A"), ("materialize", "B"),
    ]
    assert runtime.jobs_overlapped == 1
    assert runtime.stats()["overlap_fraction"] == 0.5

    events, runtime = run_pair("same", "same")
    assert events == [
        ("dispatch", "A"), ("materialize", "A"),
        ("dispatch", "B"), ("materialize", "B"),
    ]
    assert runtime.jobs_overlapped == 0


def test_padding_accounting_parity_and_buckets():
    """The off-loop path must account padding exactly like the old on-loop
    path, and track per-bucket compile/hit telemetry."""

    async def main():
        def process(inputs):
            return [inputs[0]]

        pool = TaskPool(process, "p", max_batch_size=16, batch_timeout=0.0)
        runtime = Runtime()
        runtime.attach_loop(asyncio.get_running_loop())
        runtime.start()
        pool.start(runtime)
        # 3 rows → bucket 4 (1 pad row); then 5 rows → bucket 8 (3 pad);
        # then 3 rows again → bucket 4 is now a cache hit
        for rows in (3, 5, 3):
            await pool.submit_task(np.zeros((rows, 2), np.float32))
        runtime.shutdown()
        assert pool.total_rows == 11
        assert pool.padded_rows == (4 - 3) + (8 - 5) + (4 - 3)
        assert pool.batches_formed == 3
        assert pool.padding_waste == pool.padded_rows / (11 + pool.padded_rows)
        bs = pool.bucket_stats()
        assert bs["batches_per_bucket"] == {4: 2, 8: 1}
        assert bs["cold_compiles"] == 2 and bs["cache_hits"] == 1

    run_pool(main())


def test_stale_padding_rezeroed_on_buffer_reuse():
    """A recycled staging buffer holds the previous batch's rows — the pad
    region must read as zeros, not stale data."""

    async def main():
        pad_sums = []

        def process(inputs):
            pad_sums.append(float(np.abs(inputs[0][3:]).sum()))  # pad rows
            return [inputs[0]]

        pool = TaskPool(process, "p", max_batch_size=8, batch_timeout=0.05)
        runtime = Runtime()
        runtime.attach_loop(asyncio.get_running_loop())
        runtime.start()
        pool.start(runtime)
        # two tasks → stacked batch of 3 rows in a 4-bucket, all ones
        a, b = np.ones((2, 2), np.float32), np.ones((1, 2), np.float32)
        await asyncio.gather(pool.submit_task(a), pool.submit_task(b))
        # same shape again: reuses the dirty buffer
        await asyncio.gather(pool.submit_task(a), pool.submit_task(b))
        runtime.shutdown()
        # every batch's pad region (if any) must read as zeros
        assert pad_sums and all(s == 0.0 for s in pad_sums)
        assert runtime.staging.reused >= 1

    run_pool(main())


def test_output_aliasing_staging_buffer_is_copied():
    """A process_fn returning its input (a view of the staging buffer)
    must not hand clients memory that a later batch will overwrite."""

    async def main():
        def process(inputs):
            return [inputs[0]]  # alias of the staging buffer

        pool = TaskPool(process, "p", max_batch_size=8, batch_timeout=0.05)
        runtime = Runtime()
        runtime.attach_loop(asyncio.get_running_loop())
        runtime.start()
        pool.start(runtime)
        a = np.full((2, 2), 7.0, np.float32)
        b = np.full((1, 2), 9.0, np.float32)
        (out_a,), (out_b,) = await asyncio.gather(
            pool.submit_task(a), pool.submit_task(b)
        )
        # overwrite the same bucket with different values
        c = np.full((3, 2), -1.0, np.float32)
        await pool.submit_task(c)
        runtime.shutdown()
        np.testing.assert_array_equal(out_a, a)
        np.testing.assert_array_equal(out_b, b)

    run_pool(main())


def test_mixed_dtype_tasks_promote_like_concatenate():
    """Old-path parity: co-batched tasks of different float dtypes promote
    via np.result_type (f32 + f64 → f64 batch), they do not fail the
    innocent co-batched request."""

    async def main():
        seen_dtypes = []

        def process(inputs):
            seen_dtypes.append(inputs[0].dtype)
            return [inputs[0] * 2]

        pool = TaskPool(process, "p", max_batch_size=8, batch_timeout=0.05)
        runtime = Runtime()
        runtime.attach_loop(asyncio.get_running_loop())
        runtime.start()
        pool.start(runtime)
        a = np.ones((2, 2), np.float32)
        b = np.ones((1, 2), np.float64)
        (out_a,), (out_b,) = await asyncio.gather(
            pool.submit_task(a), pool.submit_task(b)
        )
        runtime.shutdown()
        np.testing.assert_array_equal(out_a, a * 2)
        np.testing.assert_array_equal(out_b, b * 2)
        # both puts land on the loop before the manager wakes, and the
        # 50 ms grace window dwarfs a loop tick: the tasks co-batch, and
        # the mixed batch must have promoted to f64 (concatenate parity)
        assert pool.batches_formed == 1, seen_dtypes
        assert seen_dtypes == [np.float64]

    run_pool(main())


def test_many_concurrent_clients_stress():
    async def main():
        def process(inputs):
            return [inputs[0] * 3.0]

        pool = TaskPool(process, "p", max_batch_size=32, batch_timeout=0.002)
        runtime = Runtime()
        runtime.attach_loop(asyncio.get_running_loop())
        runtime.start()
        pool.start(runtime)
        xs = [np.random.randn(np.random.randint(1, 5), 3).astype(np.float32) for _ in range(100)]
        outs = await asyncio.gather(*(pool.submit_task(x) for x in xs))
        runtime.shutdown()
        for x, (out,) in zip(xs, outs):
            np.testing.assert_allclose(out, x * 3.0, rtol=1e-6)
        assert pool.batches_formed >= 1

    run_pool(main())
