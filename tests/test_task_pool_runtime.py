"""Tests for the cross-request batcher + single-consumer device loop (M0)."""

import asyncio
import threading

import numpy as np
import pytest

from learning_at_home_tpu.server import Runtime, TaskPool, bucket_rows


def test_bucket_rows():
    assert bucket_rows(1, 64) == 1
    assert bucket_rows(2, 64) == 2
    assert bucket_rows(3, 64) == 4
    assert bucket_rows(33, 64) == 64
    assert bucket_rows(64, 64) == 64
    assert bucket_rows(100, 64) == 64  # clamped


def run_pool(coro):
    return asyncio.run(coro)


def test_single_task_roundtrip():
    async def main():
        calls = []

        def process(inputs):
            calls.append(tuple(a.shape for a in inputs))
            return [inputs[0] * 2]

        pool = TaskPool(process, "p", max_batch_size=8, batch_timeout=0.001)
        runtime = Runtime()
        runtime.attach_loop(asyncio.get_running_loop())
        runtime.start()
        pool.start(runtime)
        x = np.arange(6, dtype=np.float32).reshape(3, 2)
        (out,) = await pool.submit_task(x)
        runtime.shutdown()
        np.testing.assert_array_equal(out, x * 2)
        # 3 rows → padded to bucket 4
        assert calls == [((4, 2),)]
        assert pool.padded_rows == 1 and pool.total_rows == 3

    run_pool(main())


def test_cross_request_batching():
    """Concurrent tasks coalesce into one padded device batch."""

    async def main():
        batch_rows = []

        def process(inputs):
            batch_rows.append(inputs[0].shape[0])
            return [inputs[0] + 1]

        pool = TaskPool(process, "p", max_batch_size=64, batch_timeout=0.05)
        runtime = Runtime()
        runtime.attach_loop(asyncio.get_running_loop())
        runtime.start()
        pool.start(runtime)

        xs = [np.full((3, 2), i, np.float32) for i in range(5)]
        outs = await asyncio.gather(*(pool.submit_task(x) for x in xs))
        runtime.shutdown()
        for i, (out,) in enumerate(outs):
            np.testing.assert_array_equal(out, xs[i] + 1)
        # 5 tasks × 3 rows = 15 → one batch bucketed to 16
        assert batch_rows == [16]
        assert pool.batches_formed == 1

    run_pool(main())


def test_oversized_task_rejected():
    async def main():
        pool = TaskPool(lambda i: [i[0]], "p", max_batch_size=4)
        with pytest.raises(ValueError):
            await pool.submit_task(np.zeros((5, 1), np.float32))

    run_pool(main())


def test_error_propagates_to_futures():
    async def main():
        def process(inputs):
            raise RuntimeError("device on fire")

        pool = TaskPool(process, "p", max_batch_size=4, batch_timeout=0.001)
        runtime = Runtime()
        runtime.attach_loop(asyncio.get_running_loop())
        runtime.start()
        pool.start(runtime)
        with pytest.raises(RuntimeError, match="device on fire"):
            await pool.submit_task(np.zeros((1, 1), np.float32))
        runtime.shutdown()

    run_pool(main())


def test_priority_oldest_first():
    """Runtime drains jobs oldest-submission-first across pools."""

    async def main():
        order = []

        def mk(name):
            def process(inputs):
                order.append(name)
                return [inputs[0]]

            return process

        pool_a = TaskPool(mk("a"), "a", max_batch_size=2, batch_timeout=0.0)
        pool_b = TaskPool(mk("b"), "b", max_batch_size=2, batch_timeout=0.0)
        runtime = Runtime()
        runtime.attach_loop(asyncio.get_running_loop())
        # don't start the runtime yet: let both pools enqueue first
        fut_a = asyncio.ensure_future(pool_a.submit_task(np.zeros((1, 1), np.float32)))
        await asyncio.sleep(0.01)
        fut_b = asyncio.ensure_future(pool_b.submit_task(np.zeros((1, 1), np.float32)))
        await asyncio.sleep(0.01)
        pool_a.start(runtime)
        pool_b.start(runtime)
        await asyncio.sleep(0.05)  # managers form both jobs into the queue
        runtime.start()
        await asyncio.gather(fut_a, fut_b)
        runtime.shutdown()
        assert order == ["a", "b"]  # a arrived first

    run_pool(main())


def test_batcher_property_randomized():
    """Property test (SURVEY §5.2): under random task sizes, arrival jitter,
    and pool parameters, every task gets EXACTLY its own rows back —
    batching must never mix, drop, or reorder rows within a task."""
    import random

    rng = random.Random(0)
    for trial in range(5):
        max_bs = rng.choice([4, 8, 32])
        timeout = rng.choice([0.0, 0.001, 0.01])

        async def main():
            def process(inputs):
                # tag rows so misrouting is detectable: f(x) = x * 2 + 1
                return [inputs[0] * 2 + 1]

            pool = TaskPool(
                process, "prop", max_batch_size=max_bs, batch_timeout=timeout
            )
            runtime = Runtime()
            runtime.attach_loop(asyncio.get_running_loop())
            runtime.start()
            pool.start(runtime)

            async def one_task(i):
                n = rng.randint(1, max_bs)
                # unique payload per task: task id in col 0, row id in col 1
                x = np.stack(
                    [np.full(n, i, np.float32), np.arange(n, dtype=np.float32)],
                    axis=1,
                )
                if rng.random() < 0.5:
                    await asyncio.sleep(rng.random() * 0.01)
                (out,) = await pool.submit_task(x)
                np.testing.assert_array_equal(out, x * 2 + 1)

            await asyncio.gather(*(one_task(i) for i in range(40)))
            runtime.shutdown()

        run_pool(main())


def test_many_concurrent_clients_stress():
    async def main():
        def process(inputs):
            return [inputs[0] * 3.0]

        pool = TaskPool(process, "p", max_batch_size=32, batch_timeout=0.002)
        runtime = Runtime()
        runtime.attach_loop(asyncio.get_running_loop())
        runtime.start()
        pool.start(runtime)
        xs = [np.random.randn(np.random.randint(1, 5), 3).astype(np.float32) for _ in range(100)]
        outs = await asyncio.gather(*(pool.submit_task(x) for x in xs))
        runtime.shutdown()
        for x, (out,) in zip(xs, outs):
            np.testing.assert_allclose(out, x * 3.0, rtol=1e-6)
        assert pool.batches_formed >= 1

    run_pool(main())
