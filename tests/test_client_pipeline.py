"""The pipelined client dispatch path (PR 2): off-loop serialization,
pack-once fan-out byte parity, protocol-v2 multiplexing, v1 fallback, and
the explicit quorum-straggler cancel marker.

Mirrors PR 1's server-side no-stacking-on-loop regression pattern on the
client: the ``lah-client`` event loop must only ever write pre-serialized
buffers — every wire cast and spec/blob walk happens on the caller's host
thread."""

import asyncio
import threading

import numpy as np
import optax
import pytest

from learning_at_home_tpu.client import RemoteExpert, reset_client_rpc
from learning_at_home_tpu.client.moe import RemoteMixtureOfExperts
from learning_at_home_tpu.client.routing import StaticExpertSource
from learning_at_home_tpu.client.rpc import (
    client_loop,
    pool_registry,
    set_dispatch_mode,
)
from learning_at_home_tpu.server import background_server
from learning_at_home_tpu.utils import serialization as ser
from learning_at_home_tpu.utils.connection import (
    QUORUM_STRAGGLER_CANCEL,
    ConnectionPool,
    PoolRegistry,
)
from learning_at_home_tpu.utils.serialization import (
    WireTensors,
    frame_nbytes,
    pack_frames,
    pack_message,
    peek_header,
    recv_frame,
    send_frame,
    send_frame_parts,
    unpack_message,
    wire_cast,
)

HID = 16


@pytest.fixture(autouse=True)
def _pipelined_mode():
    """Every test starts (and the suite continues) in the default mode."""
    set_dispatch_mode("pipelined")
    yield
    set_dispatch_mode("pipelined")


# ---------------------------------------------------------------------------
# wire-format building blocks
# ---------------------------------------------------------------------------


def test_pack_frames_byte_parity_with_pack_message():
    """The vectored path must put EXACTLY the v1 bytes on the wire: a
    joined pack_frames frame equals the outer length prefix +
    pack_message payload, for every tensor mix."""
    tensors = [
        np.random.RandomState(0).randn(4, 8).astype(np.float32),
        np.arange(6, dtype=np.int32),
        np.array(2.5, dtype=np.float64),
    ]
    meta = {"uid": "ffn.3", "k": 2}
    payload = pack_message("forward", tensors, meta)
    joined = b"".join(
        bytes(p) for p in pack_frames("forward", WireTensors.prepare(tensors), meta)
    )
    import struct

    assert joined == struct.pack("<I", len(payload)) + payload


def test_pack_once_fanout_byte_parity():
    """Per-uid payloads sliced from ONE whole-batch wire cast must be
    byte-identical to per-call casting of each uid's rows (the legacy
    path) — pack-once changes where the work happens, never the bytes."""
    rs = np.random.RandomState(1)
    x = rs.randn(16, HID).astype(np.float32)
    jobs = {"a": np.array([0, 3, 5]), "b": np.array([3, 5, 9, 15])}
    for wd in (None, "bfloat16", "float16"):
        x_wire = wire_cast([x], wd)[0]  # pack-once: one batch downcast
        for rows in jobs.values():
            once = b"".join(
                bytes(p)
                for p in pack_frames(
                    "forward", WireTensors.prepare([x_wire[rows]]), {"u": 1}
                )
            )
            per_call = b"".join(
                bytes(p)
                for p in pack_frames(
                    "forward",
                    WireTensors.prepare(wire_cast([x[rows]], wd)),
                    {"u": 1},
                )
            )
            assert once == per_call


def test_wiretensors_concat_shares_blobs():
    """The merged multi request is a reference concat: no tensor bytes
    are copied when k per-uid payloads combine into one frame."""
    a = WireTensors.prepare([np.ones((2, 4), np.float32)])
    b = WireTensors.prepare([np.zeros((3, 4), np.float32)])
    merged = WireTensors.concat([a, b])
    assert merged.blobs[0] is a.blobs[0]
    assert merged.blobs[1] is b.blobs[0]
    assert merged.nbytes == a.nbytes + b.nbytes


def test_rid_tagged_frame_roundtrip():
    """v2 frames carry a request id in the header; unpack ignores it and
    peek_header surfaces it."""
    t = np.random.RandomState(0).randn(3, 4).astype(np.float32)
    parts = pack_frames("forward", WireTensors.prepare([t]), {"uid": "x"}, rid=77)
    payload = b"".join(bytes(p) for p in parts)[4:]
    assert peek_header(payload) == ("forward", 77)
    msg_type, tensors, meta = unpack_message(payload)
    assert msg_type == "forward" and meta == {"uid": "x"}
    np.testing.assert_array_equal(tensors[0], t)
    # v1 frames have no rid
    v1 = pack_message("forward", [t], {"uid": "x"})
    assert peek_header(v1) == ("forward", None)


def test_send_frame_parts_wire_parity():
    """writelines and write put identical bytes on the socket."""

    async def run():
        got = []

        async def handler(reader, writer):
            got.append(await recv_frame(reader))
            got.append(await recv_frame(reader))
            writer.close()

        server = await asyncio.start_server(handler, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        t = np.arange(12, dtype=np.float32).reshape(3, 4)
        payload = pack_message("fwd", [t], {"a": 1})
        await send_frame(writer, payload)
        await send_frame_parts(
            writer, pack_frames("fwd", WireTensors.prepare([t]), {"a": 1})
        )
        await asyncio.sleep(0.1)
        writer.close()
        server.close()
        assert got[0] == got[1] == payload

    asyncio.run(run())


# ---------------------------------------------------------------------------
# no serialization on the client event loop (PR 1's pattern, client side)
# ---------------------------------------------------------------------------


def test_no_serialization_on_client_event_loop():
    """Regression: in pipelined mode, neither the wire downcast nor the
    spec/blob walk may run on the ``lah-client`` loop thread — payloads
    arrive at the loop pre-serialized.

    The old version monkeypatched ``ser.wire_cast``/``_tensor_to_wire``
    to track thread names; the invariant now lives in the sanitizer's
    ``@runs_on("host")`` assertions on ``moe._prepare_payloads`` and
    ``EncodedBatch.encode`` — any on-loop serialization is a recorded
    violation the shared conftest guard turns into a failure, and the
    site stats prove the host thread really did the packing."""
    import jax
    import jax.numpy as jnp

    from learning_at_home_tpu.utils import sanitizer

    if not sanitizer.enabled():
        pytest.skip("sanitizer disabled (LAH_SANITIZE=0)")
    before = sanitizer.site_stats()

    with background_server(
        num_experts=4, hidden_dim=HID, expert_prefix="ffn", seed=0
    ) as (endpoint, srv):
        source = StaticExpertSource({uid: endpoint for uid in srv.experts})
        moe = RemoteMixtureOfExperts(
            in_features=HID, grid_size=(4,), uid_prefix="ffn",
            source=source, k_best=2, k_min=2, wire_dtype="bfloat16",
        )
        gate = moe.init_gate_params(jax.random.PRNGKey(0))
        x = jnp.asarray(
            np.random.RandomState(0).randn(6, HID).astype(np.float32)
        )

        def loss(g, x):
            return jnp.sum(moe(x, g) ** 2)

        jax.grad(loss)(gate, x)  # forward + backward fan-out
        after = sanitizer.site_stats()

        def delta(site, cls):
            return after.get(site, {}).get(cls, 0) - before.get(
                site, {}
            ).get(cls, 0)

        # the pack really ran, and on a host thread (the io_callback
        # thread is unnamed → class "host"; the lah-client loop would
        # class as "lah-client" AND record a violation)
        assert delta("moe._prepare_payloads", "host") >= 2  # fwd + bwd
        assert delta("EncodedBatch.encode", "host") > 0
        assert delta("moe._prepare_payloads", "lah-client") == 0
        assert delta("EncodedBatch.encode", "lah-client") == 0
        assert moe.pack_bytes > 0
        assert moe.pack_bytes_saved > 0  # k=2 shares one downcast
        assert len(moe.pack_times) >= 2 and len(moe.wait_times) >= 2
    reset_client_rpc()


# ---------------------------------------------------------------------------
# protocol v2 multiplexing
# ---------------------------------------------------------------------------


def test_mux_concurrent_rpcs_share_one_socket():
    """Many concurrent RPCs on one pool must negotiate v2, interleave on
    a single connection, and each get ITS OWN reply back."""
    connections = []

    with background_server(
        num_experts=2, hidden_dim=HID, expert_prefix="nop", seed=0,
        expert_cls="nop", optimizer=optax.sgd(0.0),
    ) as (endpoint, srv):
        async def hammer():
            pool = pool_registry().get(endpoint)
            rs = np.random.RandomState(0)
            xs = [rs.randn(2, HID).astype(np.float32) for _ in range(24)]

            async def one(x):
                out, _ = await pool.rpc(
                    "forward", [x], {"uid": "nop.0"}, timeout=30.0
                )
                return out[0]

            outs = await asyncio.gather(*(one(x) for x in xs))
            return pool, xs, outs

        pool, xs, outs = client_loop().run(hammer())
        # a nop expert echoes its input: reply↔request binding is exact
        for x, out in zip(xs, outs):
            np.testing.assert_allclose(out, x, atol=1e-6)
        assert pool._proto == 2
        assert pool.inflight_max > 1  # RPCs really overlapped on the mux
    reset_client_rpc()


def test_mux_interleaved_out_of_order_replies():
    """The client must match replies by request id even when the server
    completes them in REVERSE arrival order."""

    async def run():
        async def handler(reader, writer):
            wlock = asyncio.Lock()
            batch = []

            async def reply_reversed():
                for payload in reversed(batch):
                    _, tensors, meta = unpack_message(payload)
                    _, rid = peek_header(payload)
                    parts = pack_frames(
                        "result", WireTensors.prepare(tensors),
                        {"echo": meta.get("i")}, rid=rid,
                    )
                    async with wlock:
                        await send_frame_parts(writer, parts)

            while True:
                try:
                    payload = await recv_frame(reader)
                except (asyncio.IncompleteReadError, ConnectionResetError):
                    break
                msg_type, rid = peek_header(payload)
                if msg_type == "hello":
                    await send_frame_parts(
                        writer,
                        pack_frames(
                            "hello_ok", WireTensors.prepare(),
                            {"features": ["mux"]}, rid=rid,
                        ),
                    )
                    continue
                batch.append(payload)
                if len(batch) == 4:  # hold replies until all 4 arrived
                    await reply_reversed()
                    batch = []

        server = await asyncio.start_server(handler, "127.0.0.1", 0)
        ep = server.sockets[0].getsockname()[:2]
        pool = ConnectionPool(ep)

        async def one(i):
            x = np.full((2, 2), i, np.float32)
            out, meta = await pool.rpc("forward", [x], {"i": i}, timeout=10)
            return i, out[0], meta["echo"]

        results = await asyncio.gather(*(one(i) for i in range(4)))
        for i, out, echo in results:
            assert echo == i
            np.testing.assert_array_equal(out, np.full((2, 2), i, np.float32))
        assert pool._proto == 2
        pool.close()
        server.close()

    asyncio.run(run())


def test_v1_fallback_against_old_protocol_server():
    """A pre-v2 server answers ``hello`` with an error frame; the pool
    must pin v1, REUSE the probe socket, and serve RPCs normally."""

    async def run():
        n_connections = [0]

        async def old_server(reader, writer):
            # the old build's handler: framed v1, no hello in its table
            n_connections[0] += 1
            while True:
                try:
                    payload = await recv_frame(reader)
                except (asyncio.IncompleteReadError, ConnectionResetError):
                    break
                msg_type, tensors, meta = unpack_message(payload)
                if msg_type == "forward":
                    await send_frame(
                        writer, pack_message("result", [tensors[0] * 2])
                    )
                else:
                    await send_frame(
                        writer,
                        pack_message(
                            "error",
                            meta={"message": f"unknown message type {msg_type!r}"},
                        ),
                    )

        server = await asyncio.start_server(old_server, "127.0.0.1", 0)
        ep = server.sockets[0].getsockname()[:2]
        pool = ConnectionPool(ep)
        x = np.ones((2, 3), np.float32)
        for _ in range(3):
            out, _ = await pool.rpc("forward", [x], {"uid": "e"}, timeout=10)
            np.testing.assert_array_equal(out[0], x * 2)
        assert pool._proto == 1
        # fallback reused the hello probe's socket: ONE connection total
        assert n_connections[0] == 1
        pool.close()
        server.close()

    asyncio.run(run())


def test_moe_numerics_identical_across_dispatch_modes():
    """Legacy (serialize-on-loop, v1) and pipelined (off-loop pack-once,
    v2 mux) regimes are transport variants of one contract: identical
    forward outputs against frozen server params."""
    import jax
    import jax.numpy as jnp

    with background_server(
        num_experts=4, hidden_dim=HID, expert_prefix="ffn", seed=0,
        optimizer=optax.sgd(0.0),
    ) as (endpoint, srv):
        source = StaticExpertSource({uid: endpoint for uid in srv.experts})
        moe = RemoteMixtureOfExperts(
            in_features=HID, grid_size=(4,), uid_prefix="ffn",
            source=source, k_best=2, k_min=2, wire_dtype="bfloat16",
        )
        gate = moe.init_gate_params(jax.random.PRNGKey(0))
        x = jnp.asarray(
            np.random.RandomState(2).randn(5, HID).astype(np.float32)
        )
        set_dispatch_mode("legacy")
        y_legacy = np.asarray(moe(x, gate))
        set_dispatch_mode("pipelined")
        y_pipe = np.asarray(moe(x, gate))
        np.testing.assert_allclose(y_legacy, y_pipe, rtol=1e-5, atol=1e-5)
    reset_client_rpc()


# ---------------------------------------------------------------------------
# quorum-straggler cancel marker (satellite: ADVICE r5 item 3)
# ---------------------------------------------------------------------------


class TestStragglerCancelMarker:
    def _black_hole(self):
        async def handler(reader, writer):
            await asyncio.sleep(60)

        return handler

    def test_marked_cancel_folds_ema_below_old_floor(self):
        """A quorum straggler cancelled FASTER than the old 0.05 s floor
        must still fold its wait into the EMA — the floor is gone; the
        marker is the signal (timeout_after_k_min < 50 ms works now)."""

        async def run():
            server = await asyncio.start_server(
                self._black_hole(), "127.0.0.1", 0
            )
            ep = server.sockets[0].getsockname()[:2]
            pool = ConnectionPool(ep, negotiate_v2=False)
            task = asyncio.ensure_future(
                pool.rpc("forward", (), {"uid": "x"}, timeout=30)
            )
            await asyncio.sleep(0.02)  # well under the old 50 ms floor
            task.cancel(msg=QUORUM_STRAGGLER_CANCEL)
            with pytest.raises(asyncio.CancelledError):
                await task
            assert pool.rtt_ema is not None and pool.rtt_ema < 0.05
            pool.close()
            server.close()

        asyncio.run(run())

    def test_unmarked_teardown_cancel_never_folds(self):
        """A teardown cancellation (no marker) says nothing about the
        peer — even when it lands long after the old floor."""

        async def run():
            server = await asyncio.start_server(
                self._black_hole(), "127.0.0.1", 0
            )
            ep = server.sockets[0].getsockname()[:2]
            pool = ConnectionPool(ep, negotiate_v2=False)
            task = asyncio.ensure_future(
                pool.rpc("forward", (), {"uid": "x"}, timeout=30)
            )
            await asyncio.sleep(0.08)  # above the old floor
            task.cancel()  # plain teardown-style cancel
            with pytest.raises(asyncio.CancelledError):
                await task
            assert pool.rtt_ema is None
            pool.close()
            server.close()

        asyncio.run(run())


# ---------------------------------------------------------------------------
# registry creation race (satellite)
# ---------------------------------------------------------------------------


def test_pool_registry_get_is_race_free_across_threads():
    """Host threads and the loop thread may race first contact; exactly
    one pool per endpoint must ever exist (EMA updates would otherwise
    land on an orphan)."""
    reg = PoolRegistry()
    ep = ("127.0.0.1", 4242)
    barrier = threading.Barrier(8)
    pools = []

    def grab():
        barrier.wait()
        pools.append(reg.get(ep))

    threads = [threading.Thread(target=grab) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(pools) == 8
    assert all(p is pools[0] for p in pools)
    assert len(reg._pools) == 1


def test_frame_nbytes_and_cap():
    parts = pack_frames(
        "fwd", WireTensors.prepare([np.zeros(10, np.float32)]), {}
    )
    joined = b"".join(bytes(p) for p in parts)
    assert frame_nbytes(parts) == len(joined)
    with pytest.raises(ValueError, match="MAX_FRAME_BYTES"):
        big = WireTensors([["uint8", [2 << 30], 2 << 30]], [])
        big.nbytes = 2 << 30  # spoof: construction of the real thing OOMs
        pack_frames("fwd", big, {})
