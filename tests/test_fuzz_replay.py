"""Schema-derived fuzzing (ISSUE 15): generation determinism, corpus
round-trip, pinned-corpus freshness, and slow live replay.

The contracts under test:

- same seed → bit-identical case list (``--replay`` of a pinned corpus
  and a fresh ``--smoke`` at its seed exercise the SAME frames);
- different seeds actually differ (the mutations are seeded, not
  constant);
- the checked-in ``tests/fuzz_corpus/<family>.json`` corpora match what
  the current schema regenerates — a wire-contract change that shifts
  the field model forces a corpus re-emit in the same PR, so the pinned
  regression set can never silently go stale;
- (slow) every pinned corpus replays clean against a live instance of
  its family: no crash, no hang, no wrongly-accepted reject probe, no
  sanitizer violation.
"""

import importlib
import json
import os

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
PKG = os.path.join(REPO, "learning_at_home_tpu")
CORPUS_DIR = os.path.join(os.path.dirname(__file__), "fuzz_corpus")

from learning_at_home_tpu.analysis.fuzz import (  # noqa: E402
    FAMILIES,
    STATEFUL_OPS,
    dump_corpus,
    generate_cases,
    load_corpus,
)

# the emit-time compaction bound (tools/lah_fuzz.py --emit-corpus):
# MiB-scale oversize-payload cases stay out of the pinned files
MAX_PINNED_HEX = 2 * 64 * 1024


def test_generation_is_deterministic_per_seed():
    a = generate_cases(0, [PKG])
    b = generate_cases(0, [PKG])
    assert [c.to_json() for c in a] == [c.to_json() for c in b]
    c = generate_cases(1, [PKG])
    assert [x.to_json() for x in a] != [x.to_json() for x in c]


def test_every_family_clears_the_floor():
    cases = generate_cases(0, [PKG])
    for fam in FAMILIES:
        fam_cases = [c for c in cases if c.family == fam]
        assert len(fam_cases) >= 200, fam
        rejects = [c for c in fam_cases if c.expect == "reject"]
        assert rejects, f"{fam} generated no expect-reject probes"
        assert not any(c.op in STATEFUL_OPS for c in fam_cases), (
            f"{fam} generated frames for a stateful op — a live barrage "
            f"would drain/mutate the instance under test"
        )


def test_corpus_roundtrip(tmp_path):
    cases = [c for c in generate_cases(3, [PKG]) if c.family == "dht"][:40]
    path = str(tmp_path / "c.json")
    dump_corpus(cases, path, meta={"seed": 3})
    back = load_corpus(path)
    assert [c.to_json() for c in back] == [c.to_json() for c in cases]


@pytest.mark.parametrize("family", FAMILIES)
def test_pinned_corpus_matches_regeneration(family):
    path = os.path.join(CORPUS_DIR, f"{family}.json")
    with open(path) as fh:
        raw = json.load(fh)
    seed = raw["meta"]["seed"]
    pinned = load_corpus(path)
    fresh = [
        c for c in generate_cases(seed, [PKG], families=(family,))
        if len(c.frame_hex) <= MAX_PINNED_HEX
    ]
    assert [c.to_json() for c in pinned] == [c.to_json() for c in fresh], (
        f"{family} corpus is stale — re-emit with "
        f"`python tools/lah_fuzz.py --emit-corpus tests/fuzz_corpus "
        f"--seed {seed}`"
    )


@pytest.mark.slow
@pytest.mark.parametrize("family", FAMILIES)
def test_pinned_corpus_replays_clean(family):
    lah_fuzz = importlib.import_module("tools.lah_fuzz")
    cases = load_corpus(os.path.join(CORPUS_DIR, f"{family}.json"))
    report = lah_fuzz.run_family(family, cases)
    assert report["failures"] == [], report
    assert report["frames"] == len(cases)
    assert report["outcomes"]["noreply"] == 0
