"""Worker for the 2-process jax.distributed smoke test (SURVEY.md §2.3
tier-a bring-up).  Launched by tests/test_multihost.py with a scrubbed CPU
env and 2 virtual devices per process; joins the coordinator, assembles a
global batch from host-local rows, runs one psum'd shard_map step and one
cross-process ShardedMixtureOfExperts forward, and prints a marker line
the parent asserts on.

Exit codes: 0 ok; 3 = environment cannot run jax.distributed on CPU
(parent skips); anything else = real failure.
"""

import sys

import faulthandler

faulthandler.dump_traceback_later(220, exit=True)

pid, nproc, addr = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]

import jax

from learning_at_home_tpu.parallel.multihost import (
    host_local_array_to_global,
    initialize_multihost,
)

try:
    initialize_multihost(addr, num_processes=nproc, process_id=pid)
except Exception as e:  # unsupported runtime -> skip, not fail
    print(f"MULTIHOST_SKIP {type(e).__name__}: {e}", flush=True)
    sys.exit(3)

import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

# version-portable shard_map (jax.experimental.shard_map ↔ jax.shard_map
# moved between releases — the same drift utils/jax_compat.py shims for
# the library modules)
from learning_at_home_tpu.utils.jax_compat import shard_map

from learning_at_home_tpu.parallel import ShardedMixtureOfExperts, make_mesh

assert jax.process_count() == nproc, jax.process_count()
n_local = len(jax.local_devices())
n_global = len(jax.devices())
assert n_global == nproc * n_local, (n_global, nproc, n_local)

# batch-bearing axis first => process-major, as multihost.py documents
mesh = make_mesh({"data": nproc, "expert": n_local})

# 1) host-local rows -> one global array in the train step's layout
local = np.full((2, 4), float(pid + 1), np.float32)
g = host_local_array_to_global(local, mesh)
assert g.shape == (2 * nproc, 4), g.shape

# 2) one psum'd step across BOTH processes
def summed(x):
    return jax.lax.psum(jnp.sum(x), ("data", "expert"))

try:
    total = jax.jit(
        shard_map(
            summed, mesh=mesh, in_specs=P(("data", "expert")), out_specs=P()
        )
    )(g)
except Exception as e:
    # some jaxlib builds bring up jax.distributed but cannot EXECUTE
    # cross-process computations on CPU ("Multiprocess computations
    # aren't implemented on the CPU backend") — same environment class
    # as an initialize failure: skip, don't fail
    if "Multiprocess computations aren't implemented" in str(e):
        print(f"MULTIHOST_SKIP {type(e).__name__}: {e}", flush=True)
        sys.exit(3)
    raise
expect = sum(8.0 * (i + 1) for i in range(nproc))  # 2x4 rows of (pid+1)
assert abs(float(total) - expect) < 1e-5, (float(total), expect)

# 3) the expert-parallel MoE program spanning processes: experts live on
# the 'expert' axis (2 per process); the all_to_all crosses the
# process boundary exactly like ICI inside a pod slice
moe = ShardedMixtureOfExperts(
    mesh, hidden_dim=4, num_experts=2 * n_local, k=2,
    dtype=jnp.float32, param_dtype=jnp.float32,
)
params = jax.jit(
    lambda k: moe.init_params(k, device_put=False),
    out_shardings=moe.param_shardings(),
)(jax.random.PRNGKey(0))
x = host_local_array_to_global(
    np.random.RandomState(0).randn(8, 4).astype(np.float32), mesh
)
y, aux = jax.jit(moe)(params, x)
y_norm = float(jnp.linalg.norm(y))  # replicated scalar: addressable
assert np.isfinite(y_norm) and np.isfinite(float(aux["aux_loss"]))

print(f"MULTIHOST_OK pid={pid} devices={n_global} moe_norm={y_norm:.4f}",
      flush=True)
