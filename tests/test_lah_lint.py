"""Self-tests for lah-lint (ISSUE 6): every rule must FIRE on its bad
corpus snippet and stay SILENT on the good one — a linter that never
fires is indistinguishable from one that works.  Plus the acceptance
gate: the package itself lints clean (violations fixed or baselined)."""

import os
import subprocess
import sys

import pytest

from learning_at_home_tpu.analysis.lint import (
    RULES,
    format_findings,
    lint_paths,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CORPUS = os.path.join(REPO, "tests", "lint_corpus")


def _rules_fired(path: str) -> set:
    return {
        f.rule for f in lint_paths([os.path.join(CORPUS, path)])
        if not f.suppressed
    }


@pytest.mark.parametrize("rule", sorted(RULES))
def test_rule_fires_on_bad_corpus(rule):
    fired = _rules_fired(f"{rule.lower()}_bad.py")
    assert rule in fired, (
        f"{rule} did not fire on its bad snippet (fired: {fired})"
    )


@pytest.mark.parametrize("rule", sorted(RULES))
def test_rule_silent_on_good_corpus(rule):
    fired = _rules_fired(f"{rule.lower()}_good.py")
    assert rule not in fired, f"{rule} false-positive on its good snippet"


def test_r1_flags_each_blocking_shape():
    findings = lint_paths([os.path.join(CORPUS, "r1_bad.py")])
    msgs = [f.message for f in findings if f.rule == "R1"]
    assert any("time.sleep" in m for m in msgs)
    assert any("open" in m for m in msgs)
    assert any("WireTensors.prepare" in m for m in msgs)
    assert any("pack_message" in m for m in msgs)


def test_r2_flags_each_deadlock_shape():
    findings = [
        f for f in lint_paths([os.path.join(CORPUS, "r2_bad.py")])
        if f.rule == "R2"
    ]
    assert len(findings) >= 3  # .result(), loop.run(), threadsafe chain
    assert any("run_coroutine_threadsafe" in f.message for f in findings)


def test_r3_is_cross_file():
    """The constant and the limit may live in different modules — the
    comparison runs over the whole linted set."""
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        with open(os.path.join(td, "consts.py"), "w") as fh:
            fh.write("MAX_RPCS_PER_ROUND = 100\n")
        with open(os.path.join(td, "transport.py"), "w") as fh:
            fh.write(
                "class Pool:\n"
                "    def __init__(self, max_inflight: int = 64):\n"
                "        self.max_inflight = max_inflight\n"
            )
        fired = {f.rule for f in lint_paths([td]) if not f.suppressed}
        assert "R3" in fired
        # without any max_inflight in scope the rule cannot evaluate
        only = lint_paths([os.path.join(td, "consts.py")])
        assert not [f for f in only if f.rule == "R3"]


def test_suppressions_baseline_findings():
    findings = lint_paths([os.path.join(CORPUS, "suppressed_ok.py")])
    r1 = [f for f in findings if f.rule == "R1"]
    assert len(r1) == 2, "both seeded findings should be detected"
    assert all(f.suppressed for f in r1), "both are baselined inline"
    # format output counts them as suppressed, not active
    text = format_findings(findings)
    assert "0 finding(s), 2 suppressed" in text


def test_package_lints_clean():
    """Acceptance: ``python tools/lah_lint.py learning_at_home_tpu/``
    exits 0 on the merged tree — every finding fixed or baselined."""
    r = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "tools", "lah_lint.py"),
            os.path.join(REPO, "learning_at_home_tpu"),
        ],
        capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 0, (
        f"package has unsuppressed lint findings:\n{r.stdout}\n{r.stderr}"
    )
    assert "lah-lint:" in r.stdout


def test_cli_fails_on_bad_corpus():
    r = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "tools", "lah_lint.py"),
            os.path.join(CORPUS, "r1_bad.py"),
        ],
        capture_output=True, text=True, timeout=60,
    )
    assert r.returncode == 1
    assert "R1" in r.stdout


def test_parse_error_is_reported_not_crashed():
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        bad = os.path.join(td, "broken.py")
        with open(bad, "w") as fh:
            fh.write("def broken(:\n")
        findings = lint_paths([bad])
        assert any(f.rule == "PARSE" for f in findings)
