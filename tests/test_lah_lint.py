"""Self-tests for lah-lint (ISSUE 6): every rule must FIRE on its bad
corpus snippet and stay SILENT on the good one — a linter that never
fires is indistinguishable from one that works.  Plus the acceptance
gate: the package itself lints clean (violations fixed or baselined)."""

import os
import subprocess
import sys

import pytest

from learning_at_home_tpu.analysis.lint import (
    RULES,
    format_findings,
    lint_paths,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CORPUS = os.path.join(REPO, "tests", "lint_corpus")


def _rules_fired(path: str) -> set:
    return {
        f.rule for f in lint_paths([os.path.join(CORPUS, path)])
        if not f.suppressed
    }


@pytest.mark.parametrize("rule", sorted(RULES))
def test_rule_fires_on_bad_corpus(rule):
    fired = _rules_fired(f"{rule.lower()}_bad.py")
    assert rule in fired, (
        f"{rule} did not fire on its bad snippet (fired: {fired})"
    )


@pytest.mark.parametrize("rule", sorted(RULES))
def test_rule_silent_on_good_corpus(rule):
    fired = _rules_fired(f"{rule.lower()}_good.py")
    assert rule not in fired, f"{rule} false-positive on its good snippet"


def test_r1_flags_each_blocking_shape():
    findings = lint_paths([os.path.join(CORPUS, "r1_bad.py")])
    msgs = [f.message for f in findings if f.rule == "R1"]
    assert any("time.sleep" in m for m in msgs)
    assert any("open" in m for m in msgs)
    assert any("WireTensors.prepare" in m for m in msgs)
    assert any("pack_message" in m for m in msgs)


def test_r2_flags_each_deadlock_shape():
    findings = [
        f for f in lint_paths([os.path.join(CORPUS, "r2_bad.py")])
        if f.rule == "R2"
    ]
    assert len(findings) >= 3  # .result(), loop.run(), threadsafe chain
    assert any("run_coroutine_threadsafe" in f.message for f in findings)


def test_r3_is_cross_file():
    """The constant and the limit may live in different modules — the
    comparison runs over the whole linted set."""
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        with open(os.path.join(td, "consts.py"), "w") as fh:
            fh.write("MAX_RPCS_PER_ROUND = 100\n")
        with open(os.path.join(td, "transport.py"), "w") as fh:
            fh.write(
                "class Pool:\n"
                "    def __init__(self, max_inflight: int = 64):\n"
                "        self.max_inflight = max_inflight\n"
            )
        fired = {f.rule for f in lint_paths([td]) if not f.suppressed}
        assert "R3" in fired
        # without any max_inflight in scope the rule cannot evaluate
        only = lint_paths([os.path.join(td, "consts.py")])
        assert not [f for f in only if f.rule == "R3"]


def test_suppressions_baseline_findings():
    findings = lint_paths([os.path.join(CORPUS, "suppressed_ok.py")])
    r1 = [f for f in findings if f.rule == "R1"]
    assert len(r1) == 2, "both seeded findings should be detected"
    assert all(f.suppressed for f in r1), "both are baselined inline"
    # format output counts them as suppressed, not active
    text = format_findings(findings)
    assert "0 finding(s), 2 suppressed" in text


def test_package_lints_clean():
    """Acceptance: ``python tools/lah_lint.py learning_at_home_tpu/``
    exits 0 on the merged tree — every finding fixed or baselined."""
    r = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "tools", "lah_lint.py"),
            os.path.join(REPO, "learning_at_home_tpu"),
        ],
        capture_output=True, text=True, timeout=120,
    )
    assert r.returncode == 0, (
        f"package has unsuppressed lint findings:\n{r.stdout}\n{r.stderr}"
    )
    assert "lah-lint:" in r.stdout


def test_cli_fails_on_bad_corpus():
    r = subprocess.run(
        [
            sys.executable,
            os.path.join(REPO, "tools", "lah_lint.py"),
            os.path.join(CORPUS, "r1_bad.py"),
        ],
        capture_output=True, text=True, timeout=60,
    )
    assert r.returncode == 1
    assert "R1" in r.stdout


def test_r13_message_names_the_field_and_op():
    findings = [
        f for f in lint_paths([os.path.join(CORPUS, "r13_bad.py")])
        if f.rule == "R13"
    ]
    assert findings, "R13 must fire on its bad corpus"
    assert any("`uid`" in f.message and "`forward`" in f.message
               for f in findings)


def test_r14_fires_on_both_gate_shapes():
    """The bad corpus seeds both skew shapes: the ungated dict wire
    form AND the literal-rid frame."""
    findings = [
        f for f in lint_paths([os.path.join(CORPUS, "r14_bad.py")])
        if f.rule == "R14"
    ]
    whats = {("wire" if "`wire`" in f.message else "rid")
             for f in findings}
    assert whats == {"wire", "rid"}


def test_r15_fires_both_directions():
    """Doc rows without a parse site AND parse sites without a doc row
    are both drift."""
    msgs = [
        f.message
        for f in lint_paths([os.path.join(CORPUS, "r15_bad.py")])
        if f.rule == "R15"
    ]
    assert any("never parses" in m for m in msgs)  # stale doc row
    assert any("no field row" in m for m in msgs)  # undocumented parse


def test_wire_rules_require_written_reason(tmp_path):
    """R12–R15 suppressions only baseline with a written reason: a bare
    ``ignore[R12]`` marker leaves the finding active, the same marker
    followed by an explanation suppresses it (ordinary rules keep the
    old contract — R1 suppresses either way)."""
    sender = (
        "class _Handler:\n"
        "    def _dispatch(self, payload, rid=None):\n"
        "        msg_type, tensors, meta = unpack_message(payload)\n"
        "        if msg_type == 'forward':\n"
        "            return meta.get('uid')\n"
        "        return None\n"
        "\n"
        "\n"
        "async def send(pool, tensors):\n"
        "    return await pool.rpc(\n"
        "        'forward', tensors,\n"
        "        {'uid': 'e',\n"
        "         'bogus': 1}}  # MARKER\n"
    )
    bare = tmp_path / "bare.py"
    bare.write_text(
        sender.replace("}}  # MARKER", "},  # lah-lint: ignore[R12]\n    )")
    )
    findings = [
        f for f in lint_paths([str(bare)]) if f.rule == "R12"
    ]
    assert findings and not findings[0].suppressed
    assert "no written reason" in findings[0].message

    reasoned = tmp_path / "reasoned.py"
    reasoned.write_text(
        sender.replace(
            "}}  # MARKER",
            "},  # lah-lint: ignore[R12] diagnostic tag, receiver "
            "ignores it\n    )",
        )
    )
    findings = [
        f for f in lint_paths([str(reasoned)]) if f.rule == "R12"
    ]
    assert findings and findings[0].suppressed


def test_wire_rules_block_comment_reason_counts(tmp_path):
    """A bare marker inside a multi-line explanatory comment block is a
    reasoned baseline — the block IS the reason."""
    src = (
        "class _Handler:\n"
        "    def _dispatch(self, payload, rid=None):\n"
        "        msg_type, tensors, meta = unpack_message(payload)\n"
        "        if msg_type == 'forward':\n"
        "            return meta.get('uid')\n"
        "        return None\n"
        "\n"
        "\n"
        "async def send(pool, tensors):\n"
        "    return await pool.rpc(\n"
        "        'forward', tensors,\n"
        "        {'uid': 'e',\n"
        "         # diagnostic tag: the receiver deliberately ignores\n"
        "         # this field, it only feeds sender-side logs\n"
        "         # lah-lint: ignore[R12]\n"
        "         'bogus': 1},\n"
        "    )\n"
    )
    path = tmp_path / "block.py"
    path.write_text(src)
    findings = [f for f in lint_paths([str(path)]) if f.rule == "R12"]
    assert findings and findings[0].suppressed


def test_parse_error_is_reported_not_crashed():
    import tempfile

    with tempfile.TemporaryDirectory() as td:
        bad = os.path.join(td, "broken.py")
        with open(bad, "w") as fh:
            fh.write("def broken(:\n")
        findings = lint_paths([bad])
        assert any(f.rule == "PARSE" for f in findings)
