"""M0 tests (BASELINE config 1): single FFN ExpertBackend fwd/bwd, no DHT.

Checks the core contract: backward returns input-grads identical to local
autodiff AND immediately applies the optimizer step (async SGD)."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from learning_at_home_tpu.models import make_expert
from learning_at_home_tpu.server import ExpertBackend

HID = 64


@pytest.fixture
def backend():
    rng = jax.random.PRNGKey(0)
    sample = jnp.zeros((2, HID))
    apply_fn, params = make_expert("ffn", HID, rng, sample)
    return ExpertBackend(
        "ffn.0", apply_fn, params, optax.sgd(0.05), max_batch_size=256
    )


def test_forward_matches_local(backend):
    x = np.random.RandomState(1).randn(8, HID).astype(np.float32)
    (out,) = backend.forward([x])
    expected = backend.apply_fn(backend.params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=1e-5)


def test_backward_grads_and_update(backend):
    rs = np.random.RandomState(2)
    x = rs.randn(8, HID).astype(np.float32)
    g = rs.randn(8, HID).astype(np.float32)

    params_before = jax.tree_util.tree_map(np.asarray, backend.params)
    # expected: plain jax.vjp against the pre-update params
    _, vjp_fn = jax.vjp(lambda p, xs: backend.apply_fn(p, xs), backend.params, x)
    expected_pgrads, expected_xgrad = vjp_fn(g)

    (xgrad,) = backend.backward([x], [g])
    np.testing.assert_allclose(
        np.asarray(xgrad), np.asarray(expected_xgrad), atol=1e-4, rtol=1e-4
    )

    # async SGD: params must have moved by -lr * grad immediately
    params_after = jax.tree_util.tree_map(np.asarray, backend.params)
    moved = jax.tree_util.tree_map(
        lambda before, after, grad: np.allclose(
            after, before - 0.05 * np.asarray(grad), atol=1e-4
        ),
        params_before,
        params_after,
        expected_pgrads,
    )
    assert all(jax.tree_util.tree_leaves(moved))
    assert backend.update_count == 1


def test_zero_padding_rows_do_not_corrupt_update(backend):
    """Padded (zero grad_output) rows must not change the param update."""
    rs = np.random.RandomState(3)
    x = rs.randn(4, HID).astype(np.float32)
    g = rs.randn(4, HID).astype(np.float32)

    x_pad = np.concatenate([x, rs.randn(4, HID).astype(np.float32)], axis=0)
    g_pad = np.concatenate([g, np.zeros((4, HID), np.float32)], axis=0)

    import copy

    rng = jax.random.PRNGKey(0)
    sample = jnp.zeros((2, HID))
    from learning_at_home_tpu.models import make_expert as mk

    apply_fn, params = mk("ffn", HID, rng, sample)
    twin = ExpertBackend("twin", apply_fn, params, optax.sgd(0.05))

    (grad_a,) = backend.backward([x], [g])
    (grad_b_full,) = twin.backward([x_pad], [g_pad])

    np.testing.assert_allclose(
        np.asarray(grad_a), np.asarray(grad_b_full)[:4], atol=1e-4, rtol=1e-4
    )
    a = jax.tree_util.tree_leaves(jax.tree_util.tree_map(np.asarray, backend.params))
    b = jax.tree_util.tree_leaves(jax.tree_util.tree_map(np.asarray, twin.params))
    for pa, pb in zip(a, b):
        np.testing.assert_allclose(pa, pb, atol=1e-4, rtol=1e-4)


def test_get_info(backend):
    info = backend.get_info()
    assert info["name"] == "ffn.0"
    assert info["num_params"] > 8 * HID * HID
    assert info["update_count"] == 0


def test_state_dict_roundtrip(backend):
    x = np.random.RandomState(5).randn(4, HID).astype(np.float32)
    g = np.ones((4, HID), np.float32)
    backend.backward([x], [g])
    snap = backend.state_dict()

    rng = jax.random.PRNGKey(9)
    apply_fn, params = make_expert("ffn", HID, rng, jnp.zeros((2, HID)))
    fresh = ExpertBackend("ffn.0", apply_fn, params, optax.sgd(0.05))
    fresh.load_state_dict(snap)
    (a,) = backend.forward([x])
    (b,) = fresh.forward([x])
    np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)
    assert fresh.update_count == 1


def test_swiglu_expert_roundtrip():
    """The swiglu zoo block serves and updates like the others."""
    rng = jax.random.PRNGKey(1)
    sample = jnp.zeros((2, HID))
    apply_fn, params = make_expert("swiglu", HID, rng, sample)
    be = ExpertBackend("sw.0", apply_fn, params, optax.sgd(0.01))
    x = np.random.RandomState(0).randn(4, HID).astype(np.float32)
    (out,) = be.forward([x])
    assert out.shape == (4, HID)
    (gx,) = be.backward([x], [np.ones((4, HID), np.float32)])
    assert np.isfinite(np.asarray(gx)).all()
    assert be.update_count == 1
