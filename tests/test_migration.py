"""Live expert migration — the placement driver's actuation primitive
(ISSUE 16): the ``migrate`` RPC moves ONE serving expert between two
live servers (handoff → bitwise-verified install → retire), the source
keeps serving through the transfer, and a swarm under continuous
dispatch load never drops a sample and never sees the uid's hoster
count dip below the configured replication floor.

The interleaving-exhaustive version of these invariants lives in the
lah-verify migration world (analysis/verify.py:explore_migration);
these tests drive the REAL stack over localhost sockets.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from learning_at_home_tpu.client import reset_client_rpc
from learning_at_home_tpu.client.moe import RemoteMixtureOfExperts
from learning_at_home_tpu.client.rpc import client_loop, pool_registry
from learning_at_home_tpu.dht import DHT
from learning_at_home_tpu.server import lifecycle
from learning_at_home_tpu.server.server import Server
from learning_at_home_tpu.utils.connection import RemoteCallError
from tests.test_lifecycle import assert_state_bitwise

HID = 16


@pytest.fixture(autouse=True)
def _reset_rpc():
    yield
    reset_client_rpc()


def _migrate_rpc(src_endpoint, uid, target, timeout=30.0, **extra):
    meta = {"uid": uid, "target": [target[0], target[1]], **extra}
    pool = pool_registry().get(src_endpoint)
    _tensors, reply = client_loop().run(
        pool.rpc("migrate", (), meta, timeout=timeout)
    )
    return reply


def _stats_placement(endpoint):
    pool = pool_registry().get(endpoint)
    _tensors, meta = client_loop().run(
        pool.rpc("stats", (), {}, timeout=10.0)
    )
    return meta.get("placement", {})


def _wait_idle(endpoint, timeout_s=20.0):
    """Poll the stats RPC (the driver's own idiom) until the source's
    one migration slot frees; returns the final placement section."""
    deadline = time.monotonic() + timeout_s
    placement = {}
    while time.monotonic() < deadline:
        placement = _stats_placement(endpoint)
        if placement.get("migration_in_flight") is None:
            return placement
        time.sleep(0.1)
    return placement


def test_migrate_rpc_moves_expert_bitwise():
    """The full RPC path: a trained expert moves a → b with params AND
    optimizer state bitwise, the source retires its copy only after the
    verified install, the bystander expert stays, and both sides'
    placement/lifecycle counters record the move."""
    srv_a = Server.create(
        expert_uids=["pl.0", "pl.1"], hidden_dim=HID, host="127.0.0.1",
        optimizer=optax.adam(1e-3), dht=None,
    )
    srv_b = Server.create(
        num_experts=0, hidden_dim=HID, host="127.0.0.1",
        optimizer=optax.adam(1e-3), dht=None,
    )
    try:
        # async updates make opt_state non-trivial (adam moments + count)
        x = np.random.RandomState(0).randn(4, HID).astype(np.float32)
        g = np.ones((4, HID), np.float32)
        srv_a.experts["pl.0"].backward([x], [g])
        srv_a.experts["pl.0"].backward([x], [g])
        want = srv_a.experts["pl.0"].state_dict()

        reply = _migrate_rpc(srv_a.endpoint, "pl.0", srv_b.endpoint)
        assert reply["started"] is True
        assert reply["uid"] == "pl.0"
        assert reply["state"] == lifecycle.SERVING

        placement = _wait_idle(srv_a.endpoint)
        assert placement["migrations_out"] == 1
        assert placement["migration_failures"] == 0
        assert placement["migration_in_flight"] is None

        # bitwise on the target, update_count carried, source retired
        got = srv_b.experts["pl.0"].state_dict()
        assert_state_bitwise(want, got)
        assert got["update_count"] == want["update_count"]
        assert "pl.0" in srv_b.migrated_in
        assert "pl.0" not in srv_a.experts
        # a migration is not a drain: the source keeps SERVING the rest
        assert srv_a.lifecycle_state == lifecycle.SERVING
        assert "pl.1" in srv_a.experts
        # the moved expert still serves from b
        fwd = np.asarray(srv_b.experts["pl.0"].forward([x])[0])
        assert np.isfinite(fwd).all()
    finally:
        srv_a.shutdown()
        srv_b.shutdown()


def test_migrate_rpc_validation_and_refusals():
    """Malformed requests are error replies (never a started move); a
    refusal that depends on the lifecycle is started=False instead."""
    srv = Server.create(
        expert_uids=["rv.0"], hidden_dim=HID, host="127.0.0.1",
        optimizer=optax.sgd(0.01), dht=None,
    )
    try:
        pool = pool_registry().get(srv.endpoint)
        # missing uid / malformed target / unknown uid → error replies
        for meta in (
            {"target": ["127.0.0.1", 1]},
            {"uid": "", "target": ["127.0.0.1", 1]},
            {"uid": "rv.0", "target": "not-an-endpoint"},
            {"uid": "rv.0", "target": ["host-only"]},
            {"uid": "ghost.0", "target": ["127.0.0.1", 1]},
        ):
            with pytest.raises(RemoteCallError):
                client_loop().run(
                    pool.rpc("migrate", (), meta, timeout=10.0)
                )
        assert srv.placement_info()["migrations_out"] == 0
        assert "rv.0" in srv.experts

        # a drained server refuses with started=False (not an error)
        srv.drain(grace=0.0, quiesce_timeout=2.0, handoff=False)
        reply = _migrate_rpc(srv.endpoint, "rv.0", ("127.0.0.1", 1))
        assert reply["started"] is False
        assert reply["state"] == lifecycle.DRAINED
    finally:
        srv.shutdown()


def test_migrate_to_dead_target_keeps_source_serving():
    """A failed handoff degrades to NO move: the failure is counted,
    the source still hosts and serves the uid."""
    srv = Server.create(
        expert_uids=["df.0"], hidden_dim=HID, host="127.0.0.1",
        optimizer=optax.sgd(0.01), dht=None,
    )
    try:
        # an unroutable target port: the handoff dies on connect/timeout
        reply = _migrate_rpc(
            srv.endpoint, "df.0", ("127.0.0.1", 1), timeout=6.0
        )
        assert reply["started"] is True  # the refusal happens in-flight
        placement = _wait_idle(srv.endpoint, timeout_s=30.0)
        assert placement["migration_failures"] == 1
        assert placement["migrations_out"] == 0
        assert "df.0" in srv.experts
        assert srv.lifecycle_state == lifecycle.SERVING
        x = np.random.RandomState(1).randn(2, HID).astype(np.float32)
        assert np.isfinite(np.asarray(srv.experts["df.0"].forward([x])[0])).all()
    finally:
        srv.shutdown()


def test_migration_under_dispatch_load_never_drops():
    """The churn-harness acceptance: a live migration while a trainer
    dispatches continuously — every uid stays hosted SOMEWHERE at every
    observation (effective replication never below 1), zero dropped
    samples end to end, and the moved expert lands bitwise."""
    boot = DHT()
    d_a = DHT(initial_peers=[boot.endpoint])
    d_b = DHT(initial_peers=[boot.endpoint])
    d_c = DHT(initial_peers=[boot.endpoint])
    srv_a = Server.create(
        expert_uids=["um.0", "um.1"], hidden_dim=HID, host="127.0.0.1",
        optimizer=optax.sgd(0.01), dht=d_a, update_period=0.4,
    )
    srv_b = Server.create(
        num_experts=0, hidden_dim=HID, host="127.0.0.1",
        optimizer=optax.sgd(0.01), dht=d_b, update_period=0.4,
    )
    try:
        deadline = time.time() + 20
        while time.time() < deadline:
            alive = d_c._loop.run(d_c._get_alive("um"))
            if "um.0" in alive and "um.1" in alive:
                break
            time.sleep(0.1)
        assert "um.0" in alive and "um.1" in alive, "never declared"

        # DHT-sourced MoE: homes must MOVE mid-test, so the client has
        # to re-resolve — k_min=1 lets the quorum absorb the stale
        # window right after retire (the um.1 leg still answers)
        moe = RemoteMixtureOfExperts(
            in_features=HID, grid_size=(2,), uid_prefix="um", source=d_c,
            k_best=2, k_min=1, forward_timeout=5.0,
            timeout_after_k_min=2.0, alive_ttl=0.5,
        )
        gate = moe.init_gate_params(jax.random.PRNGKey(0))
        x = jnp.asarray(
            np.random.RandomState(0).randn(4, HID).astype(np.float32)
        )
        want = srv_a.experts["um.0"].state_dict()

        def hosted_everywhere():
            for uid in ("um.0", "um.1"):
                assert uid in srv_a.experts or uid in srv_b.experts, (
                    f"{uid} lost: replication dipped below 1"
                )

        migrated = False
        for step in range(10):
            if step == 3:
                reply = _migrate_rpc(
                    srv_a.endpoint, "um.0", srv_b.endpoint
                )
                assert reply["started"] is True
                migrated = True
            jax.block_until_ready(moe(x, gate))
            hosted_everywhere()
        assert migrated
        placement = _wait_idle(srv_a.endpoint)
        hosted_everywhere()
        assert placement["migrations_out"] == 1
        assert placement["migration_failures"] == 0
        # the move really happened, bitwise
        assert "um.0" not in srv_a.experts
        assert_state_bitwise(want, srv_b.experts["um.0"].state_dict())
        # the in-flight-dispatch half of the invariant: NOTHING dropped
        assert moe.samples_dropped == 0
        # and the post-move swarm still answers (routed to b's copy)
        jax.block_until_ready(moe(x, gate))
        assert moe.samples_dropped == 0
    finally:
        srv_a.shutdown()
        srv_b.shutdown()
        reset_client_rpc()
        for d in (d_a, d_b, d_c, boot):
            d.shutdown()
