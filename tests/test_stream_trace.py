"""Stream-level serving traces (ISSUE 19 tentpole layer 1).

The contracts under test:

- **continuity**: ONE trace id stamps every lifecycle span of a stream
  — admission, pending wait, slot assignment, prefill chunks,
  preemption plus the token-identical recompute re-admit, coalesced
  expert dispatch, speculative verify accept markers, tokens, and the
  closing ``gateway.stream`` umbrella — even when the stream is evicted
  and re-queued mid-flight;
- **nesting**: the umbrella span contains every other span of its
  stream by time containment (what the merged Chrome trace renders);
- **echo**: ``gen_submit`` and ``gen_poll`` replies carry the trace so
  callers can join client-side and gateway-side spans;
- **zero cost off**: with profiling disabled no ids are minted and no
  spans recorded, while a client-supplied valid id still echoes
  (distributed callers keep their correlation even on unprofiled
  gateways) and malformed ids are dropped, never echoed.
"""

import contextlib
import time

import jax
import pytest

from learning_at_home_tpu.client import reset_client_rpc
from learning_at_home_tpu.client.routing import StaticExpertSource
from learning_at_home_tpu.gateway import Gateway, GatewayClient
from learning_at_home_tpu.models.swarm_decoder import SwarmKVDecoder
from learning_at_home_tpu.models.transformer_swarm import (
    SwarmDMoETransformerLM,
    SwarmTransformerConfig,
)
from learning_at_home_tpu.server.server import background_server
from learning_at_home_tpu.utils.profiling import (
    new_trace_id,
    timeline,
    valid_trace_id,
)

D = 16
VOCAB = 32
SEQ = 16
LAYERS = 2
UIDS = [f"ffn{layer}.{e}" for layer in range(LAYERS) for e in range(2)]


def _cfg():
    return SwarmTransformerConfig(
        vocab_size=VOCAB, d_model=D, n_layers=LAYERS, n_heads=4,
        seq_len=SEQ, grid_size=(2,), k_best=2, k_min=2, uid_prefix="ffn",
        timeout_after_k_min=30.0,
        forward_timeout=60.0, backward_timeout=60.0,
        wire_codec="none", routing_cost_weight=0,
    )


@pytest.fixture()
def swarm():
    with contextlib.ExitStack() as stack:
        endpoint, _srv = stack.enter_context(
            background_server(expert_uids=UIDS, hidden_dim=D, seed=0)
        )
        src = StaticExpertSource({u: endpoint for u in UIDS})
        model = SwarmDMoETransformerLM(_cfg(), src)
        params = model.init_params(jax.random.PRNGKey(0))
        yield model, params
    reset_client_rpc()


@pytest.fixture()
def profiled():
    timeline.enable()
    timeline.clear()
    yield
    timeline.disable()
    timeline.clear()


def _poll_done(client, sid, deadline_s=60.0):
    deadline = time.monotonic() + deadline_s
    cursor = 0
    tokens = []
    while time.monotonic() < deadline:
        out = client.poll(sid, cursor)
        tokens.extend(out.get("tokens") or [])
        cursor = int(out.get("cursor") or cursor)
        if out.get("done"):
            out["tokens"] = tokens
            return out
        time.sleep(0.01)
    raise AssertionError(f"stream {sid} never finished")


def _spans_by_trace(tid):
    return [s for s in timeline.spans() if s[3] == tid]


# a self-revisiting continuation so the n-gram drafter accepts something
REPETITIVE = [5, 6, 7, 5, 6, 7, 5, 6]


def test_one_trace_id_through_preempt_coalesce_and_spec_verify(
    swarm, profiled
):
    """Two traced streams into a pool too small for both (11 usable
    pages vs 2 × 8-page streams decoding in lockstep) on a coalescing,
    speculative gateway: the victim's trace id survives eviction and the
    recompute re-admit, and every span of each stream nests inside its
    umbrella.  (The pool is NOT the 9-page squeeze of the paged-KV
    preemption test: spec lookahead raises page demand enough that two
    streams there evict each other forever.)"""
    model, params = swarm
    prompts = [REPETITIVE, [1, 2, 1, 2, 1, 2, 1, 2]]
    n_new = SEQ - len(REPETITIVE)
    with Gateway(
        model, params, max_slots=2, max_pending=64,
        page_len=2, num_pages=12, prefix_cache=False,
        prefill_chunk_tokens=4, coalesce=True,
        spec_k=3, spec_drafter="ngram",
    ) as gw:
        client = GatewayClient(gw.endpoint)
        tids = [new_trace_id(), new_trace_id()]
        # enqueue directly on the scheduler (admission would serialise
        # the streams and hide the contention that forces preemption)
        sids = [
            gw.scheduler.submit(p, n_new, trace=t)
            for p, t in zip(prompts, tids)
        ]
        for sid, tid in zip(sids, tids):
            out = _poll_done(client, sid)
            assert out.get("error") is None, out
            assert out["trace"] == tid  # gen_poll echoes the stream's id
        assert gw.scheduler.preemptions_total >= 1
        assert gw.scheduler.stats()["spec_rounds_total"] >= 1

    # --- continuity: the full lifecycle rides each stream's one id ---
    for tid in tids:
        names = {s[0] for s in _spans_by_trace(tid)}
        assert "gateway.stream" in names, names
        assert "gateway.pending.wait" in names
        assert "gateway.prefill.chunk" in names
        assert "gateway.token.first" in names
        assert names & {"gateway.slot.assign", "gateway.recompute.admit"}
        # spec verify rounds stamp per-stream accepted-k markers
        assert any(n.startswith("gateway.spec.accept.k") for n in names), (
            names
        )

    # the victim's eviction AND its recompute re-admit share its id
    preempted = {s[3] for s in timeline.spans("gateway.preempt")}
    assert preempted and preempted <= set(tids)
    for tid in preempted:
        names = {s[0] for s in _spans_by_trace(tid)}
        assert "gateway.recompute.admit" in names

    # coalesced expert dispatch fan-out joins some stream's trace (the
    # group rides its anchoring member's id)
    fires = [s for s in timeline.spans("client.dispatch.fire") if s[3]]
    assert fires and {s[3] for s in fires} <= set(tids)

    # --- nesting: every gateway lifecycle span sits inside the stream
    # umbrella.  client.* wire spans carry the trace purely for
    # correlation: a coalesced GROUP rides its anchoring member's id, so
    # a fan-out serving the group's survivors may outlive the anchor's
    # umbrella — correlation, not containment, is their contract.
    eps = 0.05
    for tid in tids:
        spans = _spans_by_trace(tid)
        umbrella = [s for s in spans if s[0] == "gateway.stream"]
        assert len(umbrella) == 1
        _, u_start, u_dur, _, _ = umbrella[0]
        for name, start, dur, _, _ in spans:
            if not name.startswith("gateway."):
                continue
            assert start >= u_start - eps, (name, tid)
            assert start + dur <= u_start + u_dur + eps, (name, tid)


def test_cancel_marker_carries_trace(swarm, profiled):
    model, params = swarm
    with Gateway(model, params, max_slots=1, max_pending=8) as gw:
        client = GatewayClient(gw.endpoint)
        tid = new_trace_id()
        sub = client.submit([1, 2, 3], 8, trace=tid)
        assert sub.get("accepted") and sub["trace"] == tid
        assert client.cancel(sub["sid"])
    cancels = timeline.spans("gateway.stream.cancel")
    assert any(s[3] == tid for s in cancels)
    # the umbrella still closes, on the same id
    assert any(s[3] == tid for s in timeline.spans("gateway.stream"))


def test_disabled_profiling_mints_nothing_but_echoes_valid_ids(swarm):
    model, params = swarm
    timeline.disable()
    timeline.clear()
    with Gateway(model, params, max_slots=2) as gw:
        client = GatewayClient(gw.endpoint)
        # no caller id + profiling off → no id minted anywhere
        sub = client.submit([1, 2, 3], 2)
        assert sub.get("accepted") and "trace" not in sub
        out = _poll_done(client, sub["sid"])
        assert "trace" not in out
        # a valid caller-supplied id still echoes end to end
        tid = new_trace_id()
        assert valid_trace_id(tid)
        sub = client.submit([1, 2, 3], 2, trace=tid)
        assert sub["trace"] == tid
        assert _poll_done(client, sub["sid"])["trace"] == tid
        # malformed ids are dropped, never echoed back
        for bad in ("ZZZZZZZZZZZZZZZZ", "abc", "A" * 16, "0" * 17):
            sub = client.submit([1, 2, 3], 2, trace=bad)
            assert sub.get("accepted") and "trace" not in sub, bad
            _poll_done(client, sub["sid"])
    assert timeline.spans() == []  # zero spans recorded while disabled
