"""Future-based async dispatch + communication/compute overlap (ISSUE 7).

Covers the four contracts of the fire/join refactor:

- bitwise parity: the serial and overlapped schedules of the shortcut
  swarm step run the SAME primitive ops, so outputs, losses, gradients
  and updated params must be bit-identical (twin servers — per-uid
  crc32 param seeding makes two processes host identical experts, so
  each arm's backward updates can't contaminate the other's);
- measured overlap: with injected per-pool chaos latency, the overlapped
  schedule hides trunk compute inside the in-flight RPC window and the
  layer's ``overlap_fraction`` observable goes positive (and stays ~0 in
  the serial schedule);
- backward reuse: the backward fan-out resends the forward's
  already-encoded session rows (pack-once contract survives the split);
- clean failure: a stalled pool under the future-based path makes the
  join TIME OUT with a diagnosable error — the ROUND5 io_callback-hang
  class retired by construction (the legacy arm keeps the PR-5 watchdog,
  demoted to a regression role).
"""

import asyncio
import contextlib
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from learning_at_home_tpu.client import reset_client_rpc
from learning_at_home_tpu.client.moe import (
    MoEDispatchError,
    RemoteMixtureOfExperts,
)
from learning_at_home_tpu.client.routing import StaticExpertSource
from learning_at_home_tpu.client.rpc import (
    DispatchJoinTimeout,
    set_dispatch_mode,
)
from learning_at_home_tpu.models.transformer_swarm import (
    SwarmDMoETransformerLM,
    SwarmTransformerConfig,
)
from learning_at_home_tpu.server import ChaosConfig
from learning_at_home_tpu.server.server import background_server

D = 16
VOCAB = 32
SEQ = 8
LAYERS = 2
UIDS = [f"ffn{layer}.{e}" for layer in range(LAYERS) for e in range(2)]


def _cfg(**overrides):
    base = dict(
        vocab_size=VOCAB, d_model=D, n_layers=LAYERS, n_heads=4,
        seq_len=SEQ, grid_size=(2,), k_best=2, k_min=1, uid_prefix="ffn",
        # generous quorum grace: determinism here requires that no honest
        # straggler is ever cancelled (all replies must land in both arms)
        timeout_after_k_min=30.0,
        forward_timeout=120.0, backward_timeout=120.0,
        # pin the codec: the adaptive selector reads per-pool RTT EMAs,
        # and the twin arms' EMAs differ — a per-arm escalation would
        # change wire precision and break bitwise parity by design
        wire_codec="none",
    )
    base.update(overrides)
    return SwarmTransformerConfig(**base)


@pytest.fixture()
def twin_swarms():
    """Two in-process servers hosting IDENTICAL experts (explicit
    ``expert_uids`` → per-uid crc32 seeding) behind ~50 ms injected
    chaos reply latency — one per schedule arm."""
    with contextlib.ExitStack() as stack:
        arms = []
        for _ in range(2):
            endpoint, srv = stack.enter_context(
                background_server(
                    expert_uids=UIDS, hidden_dim=D, seed=0,
                    chaos=ChaosConfig(base_latency=0.05),
                )
            )
            arms.append(
                (StaticExpertSource({u: endpoint for u in UIDS}), srv)
            )
        yield arms
    reset_client_rpc()


def _tree_equal(a, b) -> bool:
    leaves_a, leaves_b = jax.tree.leaves(a), jax.tree.leaves(b)
    return len(leaves_a) == len(leaves_b) and all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(leaves_a, leaves_b)
    )


def test_serial_overlapped_bitwise_parity(twin_swarms):
    """Acceptance: serial and overlapped modes produce bitwise-identical
    forward outputs AND gradients (hence identical updated params),
    across steps that include server-side expert updates."""
    (src_serial, _), (src_overlap, _) = twin_swarms
    model_s = SwarmDMoETransformerLM(_cfg(), src_serial)
    model_o = SwarmDMoETransformerLM(_cfg(), src_overlap)
    params = model_s.init_params(jax.random.PRNGKey(0))
    opt = optax.sgd(1e-2)
    step_s = model_s.make_overlapped_train_step(opt, overlap=False)
    step_o = model_o.make_overlapped_train_step(opt, overlap=True)
    ps, po = params, params
    ss, so = opt.init(params), opt.init(params)
    rs = np.random.RandomState(0)
    for step in range(2):
        ids = jnp.asarray(rs.randint(0, VOCAB, (2, SEQ)))
        tgt = jnp.asarray(rs.randint(0, VOCAB, (2, SEQ)))
        ps, ss, loss_s = step_s(ps, ss, ids, tgt)
        po, so, loss_o = step_o(po, so, ids, tgt)
        assert np.array_equal(np.asarray(loss_s), np.asarray(loss_o)), (
            f"step {step}: losses diverged"
        )
    assert _tree_equal(ps, po), "updated params diverged between schedules"
    # forward-only parity on ONE arm (no updates): the two schedules of
    # the same model instance agree bitwise
    ids = jnp.asarray(rs.randint(0, VOCAB, (2, SEQ)))
    out_s = model_s.apply_overlapped(ps, ids, overlap=False)
    out_o = model_s.apply_overlapped(ps, ids, overlap=True)
    assert np.array_equal(np.asarray(out_s), np.asarray(out_o))


def test_overlap_fraction_positive_under_delay(twin_swarms):
    """With ~50 ms injected reply latency, the overlapped schedule hides
    trunk compute inside the in-flight window: overlap_fraction > 0 and
    above the serial arm's."""
    (src_serial, _), (src_overlap, _) = twin_swarms
    model_s = SwarmDMoETransformerLM(_cfg(), src_serial)
    model_o = SwarmDMoETransformerLM(_cfg(), src_overlap)
    params = model_s.init_params(jax.random.PRNGKey(1))
    rs = np.random.RandomState(1)
    ids = jnp.asarray(rs.randint(0, VOCAB, (2, SEQ)))
    for _ in range(2):
        jax.block_until_ready(
            model_s.apply_overlapped(params, ids, overlap=False)
        )
        jax.block_until_ready(
            model_o.apply_overlapped(params, ids, overlap=True)
        )

    def frac(model):
        stats = [m.dispatch_stats() for m in model.moes]
        assert all(s["inflight_dispatches"] == 0 for s in stats)
        return max(s["overlap_fraction"] for s in stats)

    serial_frac, overlap_frac = frac(model_s), frac(model_o)
    assert overlap_frac > 0.005, (
        f"overlapped schedule hid no in-flight time: {overlap_frac}"
    )
    assert overlap_frac > serial_frac, (serial_frac, overlap_frac)


def test_backward_reuses_forward_session_rows():
    """The backward fan-out resends the forward's already-encoded session
    rows — `pack_once_bytes_saved` must grow at backward time, and the
    stored session payload is the wire-encoded (downcast) array."""
    import ml_dtypes

    with background_server(
        num_experts=2, hidden_dim=D, expert_prefix="ffn", seed=3
    ) as (endpoint, srv):
        source = StaticExpertSource({u: endpoint for u in srv.experts})
        moe = RemoteMixtureOfExperts(
            in_features=D, grid_size=(2,), uid_prefix="ffn", source=source,
            k_best=2, k_min=1, timeout_after_k_min=30.0,
            wire_dtype="bfloat16",
        )
        gate = moe.init_gate_params(jax.random.PRNGKey(0))
        rs = np.random.RandomState(0)
        x = rs.randn(4, D).astype(np.float32)
        lc = x @ np.asarray(gate["w0"])
        fut = moe.dispatch_async(x, lc)  # fire
        y, idx, mask, cid = fut.join()
        assert int(cid) >= 0
        saved_after_fwd = moe.pack_bytes_saved
        with moe._sessions_lock:
            session, _, _ = moe._sessions[int(cid)]
        assert session, "no experts answered"
        for _uid, (_ep, x_rows, _rows, _slots) in session.items():
            assert np.asarray(x_rows).dtype == ml_dtypes.bfloat16, (
                "session must store the wire-encoded rows, not f32"
            )
        gy = np.ones((4, moe.k_best, D), np.float32)
        gx = moe._host_backward(np.int32(cid), gy)
        assert gx.shape == (4, D)
        assert moe.pack_bytes_saved > saved_after_fwd, (
            "backward did not reuse the forward's encoded session rows"
        )
    reset_client_rpc()


def test_stalled_pool_join_times_out_cleanly(monkeypatch):
    """ISSUE 7 satellite: a stalled pool (accepts, never replies, ignores
    its own RPC timeout) under the future-based path must make the join
    time out with a diagnosable error — never hang.  The legacy path
    keeps the PR-5 watchdog for this (demoted to a regression role)."""
    from learning_at_home_tpu.utils import connection

    async def _stall(self, *args, **kwargs):
        await asyncio.Event().wait()  # black hole: ignores timeout=

    monkeypatch.setattr(connection.ConnectionPool, "rpc", _stall)
    monkeypatch.setattr(connection.ConnectionPool, "rpc_prepared", _stall)
    monkeypatch.setattr(
        connection.ConnectionPool, "ensure_negotiated",
        lambda self, timeout=None: _stall(self),
    )
    source = StaticExpertSource({"ffn.0": ("127.0.0.1", 1)})
    moe = RemoteMixtureOfExperts(
        in_features=8, grid_size=(1,), uid_prefix="ffn", source=source,
        k_best=1, k_min=1, forward_timeout=0.2, timeout_after_k_min=0.1,
    )
    x = np.random.RandomState(0).randn(2, 8).astype(np.float32)
    lc = np.zeros((2, 1), np.float32)
    fut = moe.dispatch_async(x, lc)
    t0 = time.monotonic()
    with pytest.raises(DispatchJoinTimeout) as excinfo:
        fut.join(timeout=1.0)
    assert time.monotonic() - t0 < 10.0, "join did not respect its deadline"
    assert "stalled" in str(excinfo.value)
    # the fan-out task was cancelled — the loop is left clean, and the
    # in-flight gauge returns to zero (on_join_exit ran)
    deadline = time.monotonic() + 5.0
    while not fut._cf.done() and time.monotonic() < deadline:
        time.sleep(0.05)
    assert fut._cf.done()
    assert moe.dispatch_stats()["inflight_dispatches"] == 0
    reset_client_rpc()


def test_evicted_ticket_drains_inflight_gauge(monkeypatch):
    """A fired-but-never-joined ticket evicted past max_sessions must
    cancel its fan-out AND drain the inflight_dispatches gauge (the
    join-exit hook runs from cancel), so lah_top never shows phantom
    in-flight dispatches after an eviction."""
    from learning_at_home_tpu.utils import connection

    async def _stall(self, *args, **kwargs):
        await asyncio.Event().wait()  # keep every fan-out pending

    monkeypatch.setattr(connection.ConnectionPool, "rpc", _stall)
    monkeypatch.setattr(connection.ConnectionPool, "rpc_prepared", _stall)
    monkeypatch.setattr(
        connection.ConnectionPool, "ensure_negotiated",
        lambda self, timeout=None: _stall(self),
    )
    source = StaticExpertSource({"ffn.0": ("127.0.0.1", 1)})
    moe = RemoteMixtureOfExperts(
        in_features=8, grid_size=(1,), uid_prefix="ffn", source=source,
        k_best=1, k_min=1, forward_timeout=0.2, timeout_after_k_min=0.1,
        max_sessions=1,
    )
    x = np.zeros((2, 8), np.float32)
    lc = np.zeros((2, 1), np.float32)
    h1 = moe._host_fire(x, lc, store_session=False)
    h2 = moe._host_fire(x, lc, store_session=False)  # evicts ticket h1
    assert moe.dispatch_stats()["inflight_dispatches"] == 1
    with pytest.raises(MoEDispatchError):
        moe._host_join(h1)  # evicted: a diagnosable error, never a hang
    with moe._sessions_lock:
        fut = moe._pending.pop(int(h2))
    with pytest.raises(DispatchJoinTimeout):
        fut.join(timeout=0.5)
    assert moe.dispatch_stats()["inflight_dispatches"] == 0
    # discard(): the error-path cleanup apply_overlapped uses when a
    # raise lands between fire and join — cancels + drains, idempotent
    h3 = moe._host_fire(x, lc, store_session=False)
    assert moe.dispatch_stats()["inflight_dispatches"] == 1
    moe.discard(None, h3)
    assert moe.dispatch_stats()["inflight_dispatches"] == 0
    moe.discard(None, h3)  # already discarded: no-op
    reset_client_rpc()


def test_join_timeout_mode_gating():
    """Pipelined joins get a hard deadline; the legacy A/B arm keeps the
    unbounded watchdog-guarded wait (PR-5 semantics)."""
    source = StaticExpertSource({"ffn.0": ("127.0.0.1", 1)})
    moe = RemoteMixtureOfExperts(
        in_features=8, grid_size=(1,), uid_prefix="ffn", source=source,
        k_best=1, k_min=1, forward_timeout=1.0, timeout_after_k_min=0.5,
    )
    assert moe._join_timeout("forward") is not None
    assert moe._join_timeout("forward") > 1.5
    set_dispatch_mode("legacy")
    try:
        assert moe._join_timeout("forward") is None
    finally:
        set_dispatch_mode("pipelined")


def test_fire_join_under_jit(twin_swarms):
    """The fire/join custom-vjp pair compiles and runs under jit (the
    handle chain keeps the callbacks ordered), and agrees bitwise with
    the eager serial schedule.  The heavyweight 2048-row repro of the
    retired ROUND5 hazard lives in test_jitted_client_regression."""
    (src, _), _ = twin_swarms
    model = SwarmDMoETransformerLM(_cfg(), src)
    params = model.init_params(jax.random.PRNGKey(2))
    rs = np.random.RandomState(2)
    ids = jnp.asarray(rs.randint(0, VOCAB, (2, SEQ)))
    eager = np.asarray(model.apply_overlapped(params, ids, overlap=False))
    jitted = jax.jit(
        lambda p, i: model.apply_overlapped(p, i, overlap=True)
    )
    out = np.asarray(jitted(params, ids))
    assert np.array_equal(eager, out)


@pytest.mark.slow
def test_jitted_client_regression():
    """Pinned repro of the ROUND5 jitted-client io_callback deadlock
    hazard, retired by the future-based dispatch core: a jitted client
    step at the 2048-row production shape against a SEPARATE-process
    server (the historical trigger: a blocking callback on the 1-core
    XLA:CPU pool while producer thunks queue behind it — intermittent
    ~50% of runs pre-refactor).  The fire callback no longer blocks on
    the network and the single join point carries a hard deadline, so
    this must now complete (or fail loudly) within the subprocess
    timeout instead of hanging."""
    import os
    import subprocess
    import sys

    from learning_at_home_tpu.utils.subproc import clean_jax_subprocess_env

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = r"""
import faulthandler
faulthandler.dump_traceback_later(240, exit=True)
import numpy as np
from learning_at_home_tpu.utils.subproc import (
    shutdown_procs, spawn_expert_servers,
)

repo = %(repo)r
# ONE real-ffn server (the shared PDEATHSIG spawn+probe helper), warmed
# at the production batch shape so the jitted steps hit a hot bucket
procs, ports = spawn_expert_servers(
    repo, "jreg", (0.0,), d_model=64, expert_cls="ffn",
    extra_args=("--warmup", "2048"),
)
port = ports[0]
try:
    import jax, jax.numpy as jnp
    from learning_at_home_tpu.client.moe import RemoteMixtureOfExperts
    from learning_at_home_tpu.client.routing import StaticExpertSource

    source = StaticExpertSource(
        {f"jreg0.{i}": ("127.0.0.1", port) for i in range(2)}
    )
    moe = RemoteMixtureOfExperts(
        in_features=64, grid_size=(2,), uid_prefix="jreg0", source=source,
        k_best=2, k_min=2, forward_timeout=120.0, timeout_after_k_min=60.0,
    )
    gate = moe.init_gate_params(jax.random.PRNGKey(0))

    @jax.jit
    def step(x, g):
        token, handle, lc = moe.fire(x, g)
        # trunk work the schedule can hide behind the in-flight RPCs
        trunk = jnp.tanh(x) @ jnp.eye(64, dtype=x.dtype)
        return moe.join(token, handle, lc) + trunk[:, None, :1] * 0.0

    rs = np.random.RandomState(0)
    for i in range(3):
        x = jnp.asarray(rs.randn(2048, 64).astype(np.float32))
        jax.block_until_ready(step(x, gate))
        print(f"iter {i} ok", flush=True)
    print("JIT_REGRESSION_OK", flush=True)
finally:
    shutdown_procs(procs)
""" % {"repo": repo}
    env = clean_jax_subprocess_env(repo)
    r = subprocess.run(
        [sys.executable, "-c", script], env=env, capture_output=True,
        text=True, timeout=420, cwd=repo,
    )
    assert "JIT_REGRESSION_OK" in r.stdout, (
        f"jitted-client regression failed/hung:\nstdout: {r.stdout[-2000:]}"
        f"\nstderr: {r.stderr[-2000:]}"
    )
