"""Quantized wire codecs (ISSUE 5): round-trip fuzz, pack-once slice
parity, decode-into-staging, adaptive selection, hello negotiation
fallback, off-loop encode regression, and the gradient-quality gate.

The contract under test (docs/PROTOCOL.md "Wire codecs"):

- ``none`` stays byte-identical to the pre-codec wire;
- ``u8``/``blockq8`` quantize to 8 bits with per-tensor headers that
  slice together with the payload (blockq8 blocks never cross the
  trailing axis), are validated as hostile input on receipt, and decode
  directly into the consumer's buffer (the server's staging path);
- quantized payloads are only OFFERED to peers whose ``hello`` echoed
  the ``codec`` feature — v1 peers and old builds transparently get the
  wire_dtype base;
- quality is measured, not asserted: backward gradient cosine ≥ 0.99
  under ``blockq8``.
"""

import asyncio
import logging
import threading
import time

import numpy as np
import optax
import pytest

from learning_at_home_tpu.client import reset_client_rpc
from learning_at_home_tpu.client.moe import RemoteMixtureOfExperts
from learning_at_home_tpu.client.routing import StaticExpertSource
from learning_at_home_tpu.client.rpc import (
    dispatch_wait_watchdog,
    pool_registry,
    reset_dispatch_watchdog,
    set_dispatch_mode,
)
from learning_at_home_tpu.server import background_server
from learning_at_home_tpu.utils import serialization as ser
from learning_at_home_tpu.utils.serialization import (
    BLOCKQ8_BLOCK,
    BLOCKQ8_CLIP,
    EncodedBatch,
    LazyDecode,
    QUANTIZED_CODECS,
    WIRE_CODECS,
    WireTensors,
    decode_wire_tensors,
    encode_wire_tensors,
    pack_frames,
    select_wire_codec,
    unpack_message,
    wire_codec_name,
)

HID = 16

SHAPES = [(0, 8), (1,), (), (5, 1), (3, 1024), (2, 1500), (7, 3, 64),
          (2048,), (16, 2, 256), (4, 1)]
FLOAT_DTYPES = [np.float32, np.float64, "bfloat16", np.float16]


@pytest.fixture(autouse=True)
def _pipelined_mode():
    set_dispatch_mode("pipelined")
    yield
    set_dispatch_mode("pipelined")


# ---------------------------------------------------------------------------
# round-trip fuzz: all codecs x dtypes x shapes (incl. 0-row, 1-element)
# ---------------------------------------------------------------------------


def _tolerance(codec: str, x32: np.ndarray) -> float:
    if x32.size == 0:
        return 0.0
    if codec == "u8":
        # half a quantization step over the per-tensor range
        return float(x32.max() - x32.min()) / 255 * 0.51 + 1e-6
    # blockq8: half a step of the WORST block's scale; values beyond
    # ±CLIP sigma clip, but randn data never reaches 6 sigma here
    nvec, last, _nb = ser._blockq8_geometry(x32.shape, BLOCKQ8_BLOCK)
    flat = x32.reshape(nvec, last)
    worst_std = 0.0
    for v in range(nvec):
        for off in range(0, last, BLOCKQ8_BLOCK):
            blk = flat[v, off: off + BLOCKQ8_BLOCK]
            worst_std = max(worst_std, float(blk.std()))
    return max(worst_std, 1.0) * (BLOCKQ8_CLIP / 127.0) * 0.51 + 1e-5


def test_codec_roundtrip_fuzz_all_dtypes_and_shapes():
    rs = np.random.RandomState(0)
    for codec in QUANTIZED_CODECS:
        for dtype in FLOAT_DTYPES:
            for shape in SHAPES:
                x = np.asarray(rs.randn(*shape) * 3 + 1, dtype=dtype)
                x32 = np.asarray(x, np.float32)
                wire, header = EncodedBatch.encode(x, codec).full()
                assert wire.shape == x.shape
                y = LazyDecode(wire, header).decode()
                assert y.shape == x.shape and y.dtype == np.float32
                if x.size:
                    # tolerance vs the f32 view the encoder actually saw
                    tol = _tolerance(codec, x32) + float(
                        np.abs(x32 - np.asarray(x, np.float32)).max()
                    )
                    assert float(np.abs(y - x32).max()) <= tol, (
                        codec, dtype, shape,
                    )


def test_codec_roundtrip_through_msgpack_frames():
    """Headers must survive the real wire (msgpack bin fields), and
    integer tensors pass through raw under every codec."""
    rs = np.random.RandomState(1)
    tensors = [
        rs.randn(4, 1025).astype(np.float32),
        np.arange(6, dtype=np.int32),
        rs.randn(3).astype(np.float32),
    ]
    for codec in WIRE_CODECS:
        wire_tensors, wmeta = encode_wire_tensors(tensors, codec)
        parts = pack_frames(
            "forward", WireTensors.prepare(wire_tensors),
            {"uid": "x", "wire": wmeta} if wmeta is not None else {"uid": "x"},
        )
        payload = b"".join(bytes(p) for p in parts)[4:]
        _, rx_tensors, rx_meta = unpack_message(payload)
        out = decode_wire_tensors(rx_tensors, rx_meta.get("wire"), lazy=False)
        np.testing.assert_array_equal(np.asarray(out[1]), tensors[1])
        assert np.asarray(out[1]).dtype == np.int32
        for got, want in zip((out[0], out[2]), (tensors[0], tensors[2])):
            got = np.asarray(got, np.float32)
            if codec == "none":
                np.testing.assert_array_equal(got, want)
            else:
                tol = 0.2 if codec in QUANTIZED_CODECS else 0.1
                assert float(np.abs(got - want).max()) <= tol


def test_codec_none_is_byte_identical_to_raw_wire():
    """The default codec must not change a single wire byte."""
    rs = np.random.RandomState(2)
    tensors = [rs.randn(8, 32).astype(np.float32),
               np.arange(5, dtype=np.int64)]
    meta = {"uid": "ffn.3"}
    base = b"".join(
        bytes(p)
        for p in pack_frames("forward", WireTensors.prepare(tensors), meta)
    )
    wire_tensors, wmeta = encode_wire_tensors(tensors, "none")
    assert wmeta is None
    assert all(w is t for w, t in zip(wire_tensors, tensors))
    again = b"".join(
        bytes(p)
        for p in pack_frames(
            "forward", WireTensors.prepare(wire_tensors), meta
        )
    )
    assert base == again


def test_codec_fuzz_mutated_frames_parse_or_raise():
    """Random mutations of a codec-framed payload must decode cleanly or
    raise ValueError — never crash, hang, or return tensors inconsistent
    with their headers (the serialization fuzz harness, codec flavor)."""
    import random

    rng = random.Random(0)
    rs = np.random.RandomState(0)
    tensors = [rs.randn(4, 700).astype(np.float32)]
    for codec in QUANTIZED_CODECS:
        wire_tensors, wmeta = encode_wire_tensors(tensors, codec)
        base = b"".join(
            bytes(p)
            for p in pack_frames(
                "forward", WireTensors.prepare(wire_tensors),
                {"wire": wmeta},
            )
        )[4:]
        for _ in range(150):
            buf = bytearray(base)
            for _ in range(rng.randint(1, 8)):
                buf[rng.randrange(len(buf))] = rng.randrange(256)
            try:
                _, rx, meta = unpack_message(bytes(buf))
                out = decode_wire_tensors(rx, meta.get("wire"), lazy=False)
            except Exception:
                continue  # clean rejection is the contract
            for t in out:
                arr = np.asarray(t)
                assert arr.dtype == np.float32 or arr is t


# ---------------------------------------------------------------------------
# pack-once slice parity + decode-into-staging
# ---------------------------------------------------------------------------


def test_encoded_batch_slice_parity():
    """A row (or row+slot) gather of ONE batch encode must decode to the
    same values as gathering the full decode — the pack-once contract."""
    rs = np.random.RandomState(3)
    x = rs.randn(32, 300).astype(np.float32)
    rows = np.array([0, 3, 3, 31, 7])
    gy = rs.randn(16, 2, 64).astype(np.float32)
    rws, slots = np.array([0, 5, 9]), np.array([1, 0, 1])
    for codec in QUANTIZED_CODECS:
        eb = EncodedBatch.encode(x, codec)
        w, h = eb.take(rows)
        wf, hf = eb.full()
        np.testing.assert_array_equal(
            LazyDecode(w, h).decode(), LazyDecode(wf, hf).decode()[rows]
        )
        ebg = EncodedBatch.encode(gy, codec)
        w, h = ebg.take((rws, slots))
        wf, hf = ebg.full()
        np.testing.assert_array_equal(
            LazyDecode(w, h).decode(),
            LazyDecode(wf, hf).decode()[rws, slots],
        )


def test_lazydecode_into_staging_buffer():
    """BatchJob.stack must land quantized task rows directly in the
    staging buffer (pad rows still re-zeroed), identical to an eager
    decode — the server-side no-f32-on-the-loop contract."""
    from learning_at_home_tpu.server.staging import StagingBuffers
    from learning_at_home_tpu.server.task_pool import BatchJob, TaskPool

    rs = np.random.RandomState(4)
    a = rs.randn(3, 128).astype(np.float32)
    b = rs.randn(2, 128).astype(np.float32)
    lazy_a = LazyDecode(*EncodedBatch.encode(a, "blockq8").full())
    lazy_b = LazyDecode(*EncodedBatch.encode(b, "u8").full())
    job = BatchJob(
        priority=0.0, seq=0, pool=None,
        task_tensors=[(lazy_a,), (lazy_b,)],
        row_spans=[(None, 0, 3), (None, 3, 5)],
        n_rows=5, target_rows=8,
        dtypes=[np.dtype(np.float32)],
    )
    staging = StagingBuffers()
    # dirty the recycled buffer to prove pad rows are re-zeroed
    dirty = staging.acquire((8, 128), np.float32)
    dirty[:] = 7.0
    staging.release([dirty])
    inputs, buffers = job.stack(staging)
    assert len(buffers) == 1 and inputs[0] is buffers[0]
    np.testing.assert_array_equal(inputs[0][:3], lazy_a.decode())
    np.testing.assert_array_equal(inputs[0][3:5], lazy_b.decode())
    np.testing.assert_array_equal(inputs[0][5:], 0.0)
    # single-task full-bucket path decodes too (zero-copy is impossible
    # for a quantized payload, but it must still happen off-loop here)
    solo = BatchJob(
        priority=0.0, seq=1, pool=None, task_tensors=[(lazy_a,)],
        row_spans=[(None, 0, 3)], n_rows=3, target_rows=3,
    )
    inputs, buffers = solo.stack(staging)
    assert buffers == []
    np.testing.assert_array_equal(inputs[0], lazy_a.decode())


def _load_header_battery():
    """Pinned hostile-header corpus (tests/fuzz_corpus, ISSUE 15) —
    the regression battery lives as data so ``lah_fuzz`` replays and
    this test drive the SAME cases."""
    import json
    import os

    path = os.path.join(os.path.dirname(__file__), "fuzz_corpus",
                        "wire_headers.json")
    with open(path) as fh:
        corpus = json.load(fh)
    assert corpus["format"] == "lah-fuzz-battery-v1"

    def resolve(v):
        if isinstance(v, dict) and "$bytes_hex" in v:
            return bytes.fromhex(v["$bytes_hex"])
        if v == "$NAN":
            return float("nan")
        if v == "$BLOCKQ8_BLOCK":
            return BLOCKQ8_BLOCK
        if isinstance(v, dict):
            return {k: resolve(x) for k, x in v.items()}
        return v

    return [(c["name"], c["target"], c["data"], resolve(c["wire"]),
             c["match"]) for c in corpus["cases"]]


def test_lazydecode_validates_hostile_headers():
    for name, target, data, wire, match in _load_header_battery():
        payload = np.zeros(tuple(data["shape"]), np.dtype(data["dtype"]))
        with pytest.raises(ValueError, match=match):
            if target == "lazy":
                LazyDecode(payload, wire)
            else:
                decode_wire_tensors([payload], wire)
            raise AssertionError(f"hostile header accepted: {name}")


# ---------------------------------------------------------------------------
# adaptive per-pool selection
# ---------------------------------------------------------------------------


class TestAdaptiveSelection:
    def test_escalation_ladder(self):
        MB = 1 << 20
        # unmeasured or fast pools never escalate
        assert select_wire_codec("forward", 10 * MB, None, None) == "none"
        assert select_wire_codec("forward", 10 * MB, 0.001, 1e6) == "none"
        # slow pool, small payload (est ≤ 100 ms): stay raw
        assert select_wire_codec("forward", 50_000, 0.2, 1e6) == "none"
        # mid payload (est ≤ 300 ms): bf16
        assert select_wire_codec("forward", 200_000, 0.2, 1e6) == "bf16"
        # big payload: quantize — activations u8, gradients blockq8
        assert select_wire_codec("forward", 10 * MB, 0.2, 1e6) == "u8"
        assert select_wire_codec("backward", 10 * MB, 0.2, 1e6) == "blockq8"
        # configured bf16 base is kept where "none" would be
        assert select_wire_codec("forward", 50_000, 0.2, 1e6,
                                 base="bf16") == "bf16"

    def test_moe_override_wins_and_requires_support(self):
        with background_server(
            num_experts=2, hidden_dim=HID, expert_prefix="sel", seed=0,
            optimizer=optax.sgd(0.0),
        ) as (endpoint, srv):
            source = StaticExpertSource(
                {uid: endpoint for uid in srv.experts}
            )
            moe = RemoteMixtureOfExperts(
                in_features=HID, grid_size=(2,), uid_prefix="sel",
                source=source, k_best=2, k_min=2, wire_codec="u8",
            )
            import jax

            gate = moe.init_gate_params(jax.random.PRNGKey(0))
            x = np.random.RandomState(0).randn(4, HID).astype(np.float32)
            # FIRST dispatch: the pool has never negotiated, so the
            # quantized override must fall back to the base codec
            import jax.numpy as jnp

            moe(jnp.asarray(x), gate)
            assert moe.codec_counts.get("none", 0) > 0
            # negotiation done → the pin takes effect
            moe(jnp.asarray(x), gate)
            assert moe.codec_counts.get("u8", 0) > 0
            pool = pool_registry().peek(endpoint)
            assert pool.supports("codec")
        reset_client_rpc()

    def test_adaptive_escalates_on_synthetic_slow_pool(self):
        """Force RTT/bandwidth EMAs to WAN-like values: the adaptive
        path (wire_codec=None) must quantize the large dispatch."""
        with background_server(
            num_experts=2, hidden_dim=256, expert_prefix="ad", seed=0,
            optimizer=optax.sgd(0.0), max_batch_size=2048,
        ) as (endpoint, srv):
            source = StaticExpertSource(
                {uid: endpoint for uid in srv.experts}
            )
            moe = RemoteMixtureOfExperts(
                in_features=256, grid_size=(2,), uid_prefix="ad",
                source=source, k_best=2, k_min=2,
            )
            import jax
            import jax.numpy as jnp

            gate = moe.init_gate_params(jax.random.PRNGKey(0))
            x = jnp.asarray(
                np.random.RandomState(0).randn(512, 256).astype(np.float32)
            )
            moe(x, gate)  # negotiate + measure
            pool = pool_registry().peek(endpoint)
            pool.rtt_ema, pool.bw_ema = 0.3, 2e6  # 2 MB/s WAN-ish link
            moe(x, gate)
            # 512 rows x 256 f32 x 2 experts / 2e6 B/s >> 80 ms → u8
            assert moe.codec_counts.get("u8", 0) > 0, moe.codec_counts
        reset_client_rpc()

    def test_adaptive_drift_between_forward_and_backward(self):
        """Backward payloads are ~2x forward, so the selector may
        escalate backward while the forward went raw — the session's f32
        rows must then travel in a form the server accepts (regression:
        an unconverted f32 input under a 'bfloat16' declaration was
        rejected by the all-floats-compressed contract).

        Sizing note: 50 rows keeps every FORWARD exchange below
        BW_MIN_SAMPLE_BYTES, so the pinned EMAs below cannot be diluted
        by a real loopback measurement between the pin and the backward
        selection — the old 512-row version re-sampled ~1.5 MB forward
        exchanges, and a fast (warm-cache) box could drag the pinned
        bandwidth above the escalation threshold: flaky by construction."""
        with background_server(
            num_experts=2, hidden_dim=256, expert_prefix="dr", seed=0,
            optimizer=optax.sgd(0.0), max_batch_size=2048,
        ) as (endpoint, srv):
            source = StaticExpertSource(
                {uid: endpoint for uid in srv.experts}
            )
            moe = RemoteMixtureOfExperts(
                in_features=256, grid_size=(2,), uid_prefix="dr",
                source=source, k_best=2, k_min=2,
            )
            import jax
            import jax.numpy as jnp

            gate = moe.init_gate_params(jax.random.PRNGKey(0))
            x = jnp.asarray(
                np.random.RandomState(0).randn(50, 256).astype(np.float32)
            )

            def loss(xx):
                return jnp.sum(moe(xx, gate) ** 2)

            jax.grad(loss)(x)  # negotiate + measure
            pool = pool_registry().peek(endpoint)
            # fwd ≈ 100 KB → ~68 ms (stays raw); bwd ≈ 200 KB → ~137 ms
            # (bf16, below the 300 ms 8-bit bar)
            pool.rtt_ema, pool.bw_ema = 0.3, 1.5e6
            gx = np.asarray(jax.grad(loss)(x))
            assert np.isfinite(gx).all() and np.abs(gx).sum() > 0
            assert moe.codec_counts.get("bf16", 0) > 0, moe.codec_counts
            assert moe.backward_samples_dropped == 0
            assert moe.samples_dropped == 0
        reset_client_rpc()


# ---------------------------------------------------------------------------
# negotiation fallback + codec-mismatch rejection
# ---------------------------------------------------------------------------


def test_v1_peer_never_offered_quantized_codec():
    """Against an old-protocol (no hello) server, a u8-pinned MoE must
    transparently serve raw payloads — and still be numerically right."""

    async def old_server(reader, writer):
        from learning_at_home_tpu.utils.serialization import (
            pack_message,
            recv_frame,
            send_frame,
        )

        while True:
            try:
                payload = await recv_frame(reader)
            except (asyncio.IncompleteReadError, ConnectionResetError):
                break
            msg_type, tensors, meta = unpack_message(payload)
            if msg_type == "multi":
                # old-build multi: echo each part's tensors doubled
                parts = [
                    {"uid": p["uid"], "ok": True, "n_tensors": 1}
                    for p in meta["parts"]
                ]
                await send_frame(
                    writer,
                    pack_message(
                        "result", [t * 2 for t in tensors],
                        {"parts": parts},
                    ),
                )
            elif msg_type == "forward":
                await send_frame(
                    writer, pack_message("result", [tensors[0] * 2])
                )
            else:
                await send_frame(
                    writer,
                    pack_message(
                        "error",
                        meta={"message": f"unknown message type {msg_type!r}"},
                    ),
                )

    loop = asyncio.new_event_loop()
    server_box = {}

    def run_loop():
        async def start():
            server_box["server"] = await asyncio.start_server(
                old_server, "127.0.0.1", 0
            )
            server_box["ep"] = server_box["server"].sockets[0].getsockname()[:2]
        loop.run_until_complete(start())
        loop.run_forever()

    t = threading.Thread(target=run_loop, daemon=True)
    t.start()
    for _ in range(100):
        if "ep" in server_box:
            break
        time.sleep(0.05)
    ep = tuple(server_box["ep"])
    try:
        source = StaticExpertSource({"old.0": ep, "old.1": ep})
        moe = RemoteMixtureOfExperts(
            in_features=HID, grid_size=(2,), uid_prefix="old",
            source=source, k_best=2, k_min=2, wire_codec="blockq8",
        )
        import jax
        import jax.numpy as jnp

        gate = moe.init_gate_params(jax.random.PRNGKey(0))
        x = np.random.RandomState(0).randn(4, HID).astype(np.float32)
        moe(jnp.asarray(x), gate)  # first: pool unknown → raw
        moe(jnp.asarray(x), gate)  # pool pinned v1 → still raw
        assert moe.codec_counts.get("blockq8", 0) == 0
        assert moe.codec_counts.get("none", 0) > 0
        pool = pool_registry().peek(ep)
        assert pool._proto == 1 and not pool.supports("codec")
    finally:
        loop.call_soon_threadsafe(loop.stop)
        reset_client_rpc()


def test_server_rejects_unknown_codec_and_mismatched_payload():
    from learning_at_home_tpu.client.rpc import client_loop
    from learning_at_home_tpu.utils.connection import RemoteCallError

    with background_server(
        num_experts=1, hidden_dim=HID, expert_prefix="rej", seed=0,
    ) as (endpoint, _srv):
        pool = pool_registry().get(endpoint)
        x = np.random.RandomState(0).randn(2, HID).astype(np.float32)

        async def call(meta, tensors):
            return await pool.rpc("forward", tensors, meta, timeout=15)

        with pytest.raises(RemoteCallError, match="unsupported wire codec"):
            client_loop().run(
                call({"uid": "rej.0", "wire": {"c": "zstd", "h": [None]}},
                     [x])
            )
        # declared u8 but payload is f32: validation must reject, not
        # silently launder
        with pytest.raises(RemoteCallError, match="uint8"):
            client_loop().run(
                call(
                    {"uid": "rej.0",
                     "wire": {"c": "u8",
                              "h": [{"c": "u8", "lo": 0.0, "sc": 1.0}]}},
                    [x],
                )
            )
    reset_client_rpc()


# ---------------------------------------------------------------------------
# off-loop encode regression (PR 2 thread-tracking pattern)
# ---------------------------------------------------------------------------


def test_no_quantize_on_client_event_loop():
    """In pipelined mode the 8-bit encode must run on the caller's host
    thread — never on the ``lah-client`` loop (and decode of quantized
    replies must not run there either).

    The old version monkeypatched ``_encode_blockq8``/``_encode_u8``/
    ``_decode_quant_into`` to track thread names; the sanitizer's
    ``runs_on("host")`` assertions on ``EncodedBatch.encode`` and
    ``LazyDecode.decode`` now carry the invariant (the conftest guard
    fails the test on any violation), and the site stats prove the
    encode/decode really happened, off-loop."""
    import jax
    import jax.numpy as jnp

    from learning_at_home_tpu.utils import sanitizer

    if not sanitizer.enabled():
        pytest.skip("sanitizer disabled (LAH_SANITIZE=0)")
    before = sanitizer.site_stats()

    with background_server(
        num_experts=4, hidden_dim=HID, expert_prefix="ffn", seed=0,
    ) as (endpoint, srv):
        source = StaticExpertSource({uid: endpoint for uid in srv.experts})
        moe = RemoteMixtureOfExperts(
            in_features=HID, grid_size=(4,), uid_prefix="ffn",
            source=source, k_best=2, k_min=2, wire_codec="blockq8",
        )
        gate = moe.init_gate_params(jax.random.PRNGKey(0))
        x = jnp.asarray(
            np.random.RandomState(0).randn(6, HID).astype(np.float32)
        )

        def loss(g, x):
            return jnp.sum(moe(x, g) ** 2)

        jax.grad(loss)(gate, x)  # negotiation dispatch (raw)
        jax.grad(loss)(gate, x)  # quantized forward + backward
        after = sanitizer.site_stats()

        def delta(site, cls):
            return after.get(site, {}).get(cls, 0) - before.get(
                site, {}
            ).get(cls, 0)

        # client-side encode happened on the host (io_callback) thread,
        # never on the lah-client loop; the server side may legitimately
        # add "runtime" (staging decode) and scoped serving-loop counts
        assert delta("EncodedBatch.encode", "host") > 0, (
            "blockq8 encode never ran on a host thread"
        )
        assert delta("EncodedBatch.encode", "lah-client") == 0
        assert delta("LazyDecode.decode", "lah-client") == 0
        decode_total = sum(
            after.get("LazyDecode.decode", {}).values()
        ) - sum(before.get("LazyDecode.decode", {}).values())
        assert decode_total > 0, "quantized payloads never decoded"
        assert moe.codec_counts.get("blockq8", 0) > 0
    reset_client_rpc()


# ---------------------------------------------------------------------------
# quality gate: backward gradient cosine under blockq8
# ---------------------------------------------------------------------------


def test_backward_gradient_cosine_blockq8():
    """Per-expert backward input-gradient cosine ≥ 0.99 vs the
    uncompressed run (frozen optimizer so both runs see one model)."""
    import jax
    import jax.numpy as jnp

    with background_server(
        num_experts=4, hidden_dim=64, expert_prefix="q", seed=0,
        optimizer=optax.sgd(0.0),
    ) as (endpoint, srv):
        source = StaticExpertSource({uid: endpoint for uid in srv.experts})
        x = jnp.asarray(
            np.random.RandomState(0).randn(32, 64).astype(np.float32)
        )
        grads = {}
        for codec in ("none", "blockq8"):
            moe = RemoteMixtureOfExperts(
                in_features=64, grid_size=(4,), uid_prefix="q",
                source=source, k_best=2, k_min=2, wire_codec=codec,
            )
            gate = moe.init_gate_params(jax.random.PRNGKey(0))

            def loss(xx):
                return jnp.sum(moe(xx, gate) ** 2)

            jax.grad(loss)(x)  # negotiation warm-up
            grads[codec] = np.asarray(jax.grad(loss)(x))
        g0, g1 = grads["none"], grads["blockq8"]
        cos = float(
            (g0 * g1).sum()
            / (np.linalg.norm(g0) * np.linalg.norm(g1) + 1e-12)
        )
        assert cos >= 0.99, f"gradient cosine {cos:.4f} < 0.99"
    reset_client_rpc()


# ---------------------------------------------------------------------------
# dispatch-wait watchdog (satellite)
# ---------------------------------------------------------------------------


class TestDispatchWatchdog:
    def test_stalled_wait_logs_stacks_once(self, caplog, monkeypatch):
        """A wait exceeding the RTT-multiple budget must WARN with thread
        stacks — once per process (a deliberately-stalled fake pool
        stands in for the silent io_callback deadlock)."""
        monkeypatch.setenv("LAH_DISPATCH_WATCHDOG_MULT", "2")
        monkeypatch.setenv("LAH_DISPATCH_WATCHDOG_MIN_S", "0.05")
        reset_dispatch_watchdog()
        with caplog.at_level(logging.WARNING,
                             logger="learning_at_home_tpu.client.rpc"):
            with dispatch_wait_watchdog(0.01, what="fake stalled pool"):
                time.sleep(0.3)  # the stalled dispatch wait
            # filter on the rpc logger: the flight recorder also WARNs
            # when it dumps its dispatch_watchdog artifact, and that
            # line legitimately contains "watchdog"
            records = [
                r for r in caplog.records
                if r.name == "learning_at_home_tpu.client.rpc"
                and "watchdog" in r.getMessage()
            ]
            assert len(records) == 1
            msg = records[0].getMessage()
            assert "fake stalled pool" in msg
            assert "thread" in msg and "File" in msg  # real stacks
            # once per process: a second stall stays silent
            with dispatch_wait_watchdog(0.01, what="second stall"):
                time.sleep(0.3)
            records = [
                r for r in caplog.records
                if r.name == "learning_at_home_tpu.client.rpc"
                and "watchdog" in r.getMessage()
            ]
            assert len(records) == 1
        reset_dispatch_watchdog()

    def test_fast_wait_never_fires(self, caplog, monkeypatch):
        monkeypatch.setenv("LAH_DISPATCH_WATCHDOG_MULT", "20")
        monkeypatch.setenv("LAH_DISPATCH_WATCHDOG_MIN_S", "0.2")
        reset_dispatch_watchdog()
        with caplog.at_level(logging.WARNING,
                             logger="learning_at_home_tpu.client.rpc"):
            with dispatch_wait_watchdog(0.001, what="fast"):
                time.sleep(0.01)
            time.sleep(0.3)  # past the budget: timer must be cancelled
            assert not [
                r for r in caplog.records if "watchdog" in r.getMessage()
            ]

    def test_disabled_without_rtt_or_multiple(self, monkeypatch):
        monkeypatch.setenv("LAH_DISPATCH_WATCHDOG_MULT", "0")
        with dispatch_wait_watchdog(10.0):
            pass
        monkeypatch.setenv("LAH_DISPATCH_WATCHDOG_MULT", "20")
        with dispatch_wait_watchdog(None):
            pass


# ---------------------------------------------------------------------------
# misc contract details
# ---------------------------------------------------------------------------


def test_wire_codec_name_labels():
    assert wire_codec_name(None) == "none"
    assert wire_codec_name("bfloat16") == "bf16"
    assert wire_codec_name("float16") == "f16"
    assert wire_codec_name({"c": "u8", "h": []}) == "u8"


def test_moe_rejects_unknown_codec():
    with pytest.raises(ValueError, match="wire codec"):
        RemoteMixtureOfExperts(
            in_features=4, grid_size=(2,), uid_prefix="x",
            source=StaticExpertSource({}), wire_codec="zstd",
        )
