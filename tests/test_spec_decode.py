"""Counter-based sampling RNG + self-speculative decoding (ISSUE 17).

The contracts under test:

- **counter-based sampling determinism**: a sampled token depends only
  on ``(logits, stream_seed, absolute position)`` — so paged == dense,
  any prefill chunk size, recompute-after-preemption and coalesced vs
  solo execution all reproduce identical sampled streams (the PR 13
  parity contracts extended past greedy);
- **exact speculation**: with ``spec_k > 0`` the gateway verifies k
  drafted tokens per batched round and commits exactly the longest
  matched prefix plus the bonus sample — output token-identical to the
  non-speculative decoder for greedy AND sampled streams, with KV pages
  rolled back past the first rejection (refcount-clean, audit-enforced);
- **hostile sampling fields**: malformed gen_submit sampling values are
  well-formed error frames, never decoder state.
"""

import contextlib
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from learning_at_home_tpu.client import reset_client_rpc
from learning_at_home_tpu.client.routing import StaticExpertSource
from learning_at_home_tpu.gateway import Gateway, GatewayClient
from learning_at_home_tpu.models.drafter import (
    NGramDrafter,
    TruncatedTrunkDrafter,
)
from learning_at_home_tpu.models.kv_pages import PagedKVCache
from learning_at_home_tpu.models.sampling import SamplingParams, sample_token
from learning_at_home_tpu.models.swarm_decoder import SwarmKVDecoder
from learning_at_home_tpu.models.transformer_swarm import (
    SwarmDMoETransformerLM,
    SwarmTransformerConfig,
)
from learning_at_home_tpu.server.server import background_server

D = 16
VOCAB = 32
SEQ = 16
LAYERS = 2
UIDS = [f"ffn{layer}.{e}" for layer in range(LAYERS) for e in range(2)]

SEEDS = [7, 19, 1234]  # the ">= 3 sampling seeds" acceptance bar


def _cfg(**overrides):
    base = dict(
        vocab_size=VOCAB, d_model=D, n_layers=LAYERS, n_heads=4,
        seq_len=SEQ, grid_size=(2,), k_best=2, k_min=2, uid_prefix="ffn",
        timeout_after_k_min=30.0,
        forward_timeout=60.0, backward_timeout=60.0,
        wire_codec="none", routing_cost_weight=0,
    )
    base.update(overrides)
    return SwarmTransformerConfig(**base)


@pytest.fixture()
def swarm():
    with contextlib.ExitStack() as stack:
        endpoint, _srv = stack.enter_context(
            background_server(expert_uids=UIDS, hidden_dim=D, seed=0)
        )
        src = StaticExpertSource({u: endpoint for u in UIDS})
        model = SwarmDMoETransformerLM(_cfg(), src)
        params = model.init_params(jax.random.PRNGKey(0))
        yield model, params
    reset_client_rpc()


def _sp(seed, **kw):
    base = dict(seed=seed, temperature=0.9, top_p=0.95, top_k=8)
    base.update(kw)
    return SamplingParams(**base)


def _poll_done(client, sid, deadline_s=60.0):
    deadline = time.monotonic() + deadline_s
    cursor = 0
    tokens = []
    while time.monotonic() < deadline:
        out = client.poll(sid, cursor)
        tokens.extend(out.get("tokens") or [])
        cursor = int(out.get("cursor") or cursor)
        if out.get("done"):
            out["tokens"] = tokens
            return out
        time.sleep(0.01)
    raise AssertionError(f"stream {sid} never finished")


# ---------------------------------------------------------------------------
# the sampling primitive itself (no swarm)
# ---------------------------------------------------------------------------


def test_sampling_params_reject_hostile_values():
    for bad in (
        dict(temperature=-0.5),
        dict(temperature=float("nan")),
        dict(temperature=float("inf")),
        dict(top_p=0.0),
        dict(top_p=1.5),
        dict(top_p=float("nan")),
        dict(top_k=-1),
        dict(seed=-1),
        dict(seed=2 ** 63),
    ):
        with pytest.raises(ValueError):
            SamplingParams(**bad)
    assert SamplingParams().greedy
    assert not SamplingParams(temperature=0.7).greedy


def test_sample_token_is_a_pure_function_of_seed_and_position():
    rng = np.random.RandomState(0)
    logits = rng.randn(VOCAB).astype(np.float32)
    sp = _sp(seed=3)
    draws = [sample_token(logits, sp, pos) for pos in range(32)]
    # deterministic under replay, regardless of call order
    for pos in reversed(range(32)):
        assert sample_token(logits, sp, pos) == draws[pos]
    # the counter actually matters: positions do not all collide
    assert len(set(draws)) > 1
    # a different stream seed is a different sequence
    other = [sample_token(logits, _sp(seed=4), pos) for pos in range(32)]
    assert draws != other


def test_sample_token_greedy_and_mask_limits():
    rng = np.random.RandomState(1)
    logits = rng.randn(VOCAB).astype(np.float32)
    argmax = int(np.argmax(logits))
    # temperature 0 / params None are bitwise argmax
    assert sample_token(logits, None, 5) == argmax
    assert sample_token(logits, SamplingParams(), 5) == argmax
    # top_k=1 collapses every draw onto the argmax
    sp1 = SamplingParams(seed=9, temperature=1.3, top_k=1)
    assert all(
        sample_token(logits, sp1, pos) == argmax for pos in range(16)
    )
    # a tiny nucleus still always keeps the top token
    spp = SamplingParams(seed=9, temperature=1.3, top_p=1e-6)
    assert all(
        sample_token(logits, spp, pos) == argmax for pos in range(16)
    )
    # top_k masks: every draw is one of the k largest logits
    spk = SamplingParams(seed=11, temperature=2.0, top_k=4)
    top4 = set(np.argsort(-logits)[:4].tolist())
    assert all(
        sample_token(logits, spk, pos) in top4 for pos in range(32)
    )


# ---------------------------------------------------------------------------
# PR 13 parity contracts, extended to sampled streams
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("seed", SEEDS)
def test_sampled_paged_vs_dense_token_parity(swarm, seed):
    model, params = swarm
    prompts = [[1, 2, 3], [4, 5], [7, 8, 9, 10, 11]]
    sampling = [_sp(seed + i) for i in range(len(prompts))]
    dense = SwarmKVDecoder(model, params, max_slots=3)
    paged = SwarmKVDecoder(
        model, params, max_slots=3, kv_layout="paged", page_len=4
    )
    out_d = dense.generate(prompts, max_new_tokens=6, sampling=sampling)
    out_p = paged.generate(prompts, max_new_tokens=6, sampling=sampling)
    assert out_d == out_p


@pytest.mark.parametrize("chunk", [1, 3, 64])
def test_sampled_chunked_prefill_token_equal_any_chunk_size(swarm, chunk):
    model, params = swarm
    prompt = [3, 1, 4, 1, 5, 9, 2, 6, 5, 3]
    sp = _sp(SEEDS[0])
    ref = SwarmKVDecoder(model, params, max_slots=1).generate(
        [prompt], max_new_tokens=4, sampling=[sp]
    )[0]
    dec = SwarmKVDecoder(
        model, params, max_slots=1, kv_layout="paged", page_len=4,
        prefix_cache=False,
    )
    dec.begin_prefill(0, prompt, stream_id="s", sampling=sp)
    toks = []
    tok = None
    while tok is None:
        _consumed, tok = dec.prefill_step(0, chunk)
    toks.append(tok)
    while len(toks) < 4:
        assert dec.ensure_decode_pages() == []
        toks.append(int(dec.decode_step()[0]))
    assert toks == ref


@pytest.mark.parametrize("seed", SEEDS)
def test_sampled_recompute_after_preemption_token_identical(swarm, seed):
    """The pool is too small for both streams' full depth, so one gets
    preempted and recomputed — with the counter-based RNG the sampled
    continuation is identical to an uncontended run (the contract greedy
    streams always had)."""
    model, params = swarm
    prompts = [[1, 2], [9, 8]]
    n_new = SEQ - 2
    sampling = {tuple(p): _sp(seed + i) for i, p in enumerate(prompts)}
    ref = {}
    for p in prompts:
        ref[tuple(p)] = SwarmKVDecoder(model, params, max_slots=1).generate(
            [p], max_new_tokens=n_new, sampling=[sampling[tuple(p)]]
        )[0]
    with Gateway(
        model, params, max_slots=2, max_pending=64,
        page_len=2, num_pages=10,  # 9 usable < 2 streams × 8 pages
        prefix_cache=False, prefill_chunk_tokens=4,
    ) as gw:
        client = GatewayClient(gw.endpoint)
        sids = [
            gw.scheduler.submit(p, n_new, sampling=sampling[tuple(p)])
            for p in prompts
        ]
        for p, sid in zip(prompts, sids):
            out = _poll_done(client, sid)
            assert out.get("error") is None, out
            assert out["tokens"] == ref[tuple(p)]
        assert gw.scheduler.preemptions_total >= 1
        assert gw.scheduler.streams_errored_total == 0


@pytest.mark.parametrize("seed", SEEDS)
def test_sampled_coalesced_vs_solo_parity(swarm, seed):
    """Coalescing groups expert fan-outs across streams; with sampling
    on, the grouped and ungrouped gateways must still emit identical
    per-stream tokens (bitwise logits + counter-keyed draws)."""
    model, params = swarm
    prompts = [[1, 2, 3], [4, 5, 6, 7], [7, 8]]
    results = {}
    for label, coalesce in (("grouped", True), ("solo", False)):
        with Gateway(model, params, max_slots=4, coalesce=coalesce) as gw:
            client = GatewayClient(gw.endpoint)
            outs = [
                client.generate(
                    p, 4, seed=seed + i, temperature=0.9,
                    top_p=0.95, top_k=8,
                )
                for i, p in enumerate(prompts)
            ]
            assert all(
                not o.get("shed") and not o.get("error") for o in outs
            )
            results[label] = [o["tokens"] for o in outs]
    assert results["grouped"] == results["solo"]


# ---------------------------------------------------------------------------
# drafters
# ---------------------------------------------------------------------------


def test_ngram_drafter_suffix_match_and_fallback():
    d = NGramDrafter(max_suffix=4)
    # repeating context: the suffix recurs, so it proposes the loop
    ctx = [1, 2, 3, 4, 1, 2, 3, 4, 1, 2]
    assert d.propose(ctx, 3) == [3, 4, 1]
    # nothing recurs → empty proposal (plain decode fallback)
    assert d.propose([1, 2, 3, 4, 5, 6], 3) == []
    assert d.propose([5], 3) == []
    assert d.propose(ctx, 0) == []


def test_truncated_trunk_drafter_shapes_and_determinism(swarm):
    model, params = swarm
    d = TruncatedTrunkDrafter(model, params, draft_layers=1, window=8)
    ctx = [3, 1, 4, 1, 5]
    out1 = d.propose(ctx, 4)
    out2 = d.propose(ctx, 4)
    assert out1 == out2
    assert len(out1) == 4
    assert all(0 <= t < VOCAB for t in out1)
    # never drafts past the position table
    long_ctx = list(range(1, SEQ))  # len SEQ-1
    assert len(d.propose(long_ctx, 8)) <= 1


# ---------------------------------------------------------------------------
# verify_step: longest-prefix acceptance + KV rollback refcounts
# ---------------------------------------------------------------------------


def _reference_tokens(model, params, prompt, n, sampling=None):
    return SwarmKVDecoder(model, params, max_slots=1).generate(
        [prompt], max_new_tokens=n, sampling=[sampling]
    )[0]


@pytest.mark.parametrize("sampling", [None, "sampled"])
def test_verify_step_accepts_longest_prefix_exactly(swarm, sampling):
    model, params = swarm
    sp = _sp(SEEDS[1]) if sampling else None
    prompt = [3, 1, 4, 1, 5]
    ref = _reference_tokens(model, params, prompt, 6, sp)
    dec = SwarmKVDecoder(
        model, params, max_slots=2, kv_layout="paged", page_len=2,
        prefix_cache=False,
    )
    first = dec.prefill_into_slot(0, prompt, stream_id="s", sampling=sp)
    assert first == ref[0]
    # draft the TRUE continuation with one poisoned position: the round
    # must accept exactly up to the poison, then the bonus sample
    drafts = [ref[1], ref[2], (ref[3] + 1) % VOCAB, ref[4]]
    assert dec.ensure_decode_pages() == []
    assert dec.ensure_lookahead_pages(0, len(drafts)) == len(drafts)
    res = dec.verify_step({0: drafts})[0]
    assert res["accepted"] == 2
    assert res["proposed"] == 4
    assert res["tokens"] == ref[1:4]  # 2 accepted drafts + bonus
    assert int(dec.pos[0]) == len(prompt) + 3
    assert int(dec.last_tok[0]) == ref[3]
    # rolled-back lookahead pages are refcount-clean
    assert dec.kv.audit() == []
    assert dec.kv.rollback_pages_total >= 1
    # a fully-correct draft round accepts everything + bonus
    drafts2 = [ref[4], ref[5]]
    assert dec.ensure_lookahead_pages(0, len(drafts2)) == len(drafts2)
    res2 = dec.verify_step({0: drafts2})[0]
    assert res2["accepted"] == 2
    assert res2["tokens"][:2] == ref[4:6]
    assert dec.kv.audit() == []
    # an empty proposal is a plain decode row
    res3 = dec.verify_step({0: []})[0]
    assert res3["accepted"] == 0 and res3["proposed"] == 0
    assert len(res3["tokens"]) == 1
    dec.evict(0)
    assert dec.kv.pages_used() - dec.kv.pages_reclaimable() <= 0


def test_verify_step_batches_multiple_streams_one_round(swarm):
    """Two streams with different draft depths verify in ONE call/round
    and each commits its own longest prefix — tokens identical to solo
    non-speculative decode."""
    model, params = swarm
    prompts = [[1, 2, 3], [9, 8]]
    refs = [_reference_tokens(model, params, p, 5) for p in prompts]
    dec = SwarmKVDecoder(
        model, params, max_slots=2, kv_layout="paged", page_len=4,
        prefix_cache=False,
    )
    for i, p in enumerate(prompts):
        assert dec.prefill_into_slot(i, p, stream_id=i) == refs[i][0]
    drafts = {
        0: [refs[0][1], (refs[0][2] + 1) % VOCAB],  # accept 1
        1: [refs[1][1], refs[1][2], refs[1][3]],    # accept all
    }
    assert dec.ensure_decode_pages() == []
    for s, d in drafts.items():
        assert dec.ensure_lookahead_pages(s, len(d)) == len(d)
    rounds0 = dec.verify_rounds_total
    res = dec.verify_step(drafts)
    assert dec.verify_rounds_total == rounds0 + 1
    assert res[0]["accepted"] == 1 and res[0]["tokens"] == refs[0][1:3]
    assert res[1]["accepted"] == 3 and res[1]["tokens"] == refs[1][1:5]
    assert dec.kv.audit() == []


def test_rollback_refcounts_and_shared_page_guard():
    kv = PagedKVCache(
        n_layers=1, n_heads=2, head_dim=4, dtype=jnp.float32,
        max_slots=2, seq_len=16, page_len=4, num_pages=8,
    )
    # private lookahead pages roll back cleanly
    for _ in range(4):
        kv.alloc_slot_page(0)
    assert kv.pages_used() == 4
    released = kv.truncate_slot(0, 6)  # keep ceil(6/4) = 2 pages
    assert released == 2
    assert int(kv.alloc_count[0]) == 2
    assert kv.pages_used() == 2
    assert kv.rollback_pages_total == 2
    assert kv.audit() == []
    # truncating into a prefix-cache-held page is a refcounting bug and
    # must raise, not silently free shared state
    assert kv.register_prefix(0, [1, 2, 3, 4, 5, 6, 7, 8]) == 2
    with pytest.raises(AssertionError, match="rollback_private_only"):
        kv.truncate_slot(0, 2)
    assert kv.audit() == []


def test_ensure_lookahead_pages_clamps_under_pressure(swarm):
    model, params = swarm
    dec = SwarmKVDecoder(
        model, params, max_slots=1, kv_layout="paged", page_len=2,
        num_pages=4, prefix_cache=False,  # 3 usable pages
    )
    dec.prefill_into_slot(0, [1, 2, 3], stream_id="s")  # pos 3, 2 pages
    assert dec.ensure_decode_pages() == []
    # pos 3 needs page 1 (held); lookahead 4 would need pages up to
    # logical 3 — only one free page remains, so the clamp bites
    k = dec.ensure_lookahead_pages(0, 4)
    assert k == 2  # pages 0..2 cover positions 0..5 → pos+2 max
    assert dec.kv.audit() == []


# ---------------------------------------------------------------------------
# gateway end-to-end: spec on == spec off, token for token
# ---------------------------------------------------------------------------

# a prompt whose continuation revisits itself so the n-gram drafter has
# something to copy (tiny random-init models loop under greedy anyway)
REPETITIVE = [5, 6, 7, 5, 6, 7, 5, 6]


def test_gateway_spec_decode_token_identical_greedy(swarm):
    model, params = swarm
    prompts = [REPETITIVE, [1, 2, 1, 2, 1], [9, 8, 9, 8]]
    results = {}
    for label, k in (("spec", 4), ("plain", 0)):
        with Gateway(
            model, params, max_slots=4, spec_k=k, spec_drafter="ngram"
        ) as gw:
            client = GatewayClient(gw.endpoint)
            outs = [client.generate(p, 6) for p in prompts]
            assert all(
                not o.get("shed") and not o.get("error") for o in outs
            )
            results[label] = [o["tokens"] for o in outs]
            if k:
                s = gw.scheduler.stats()
                assert s["spec_rounds_total"] >= 1
                assert s["spec_tokens_total"] >= 1
                assert gw.scheduler.audit() == []
    assert results["spec"] == results["plain"]


@pytest.mark.parametrize("seed", SEEDS)
def test_gateway_spec_decode_token_identical_sampled(swarm, seed):
    model, params = swarm
    prompts = [REPETITIVE, [1, 2, 1, 2, 1]]
    results = {}
    for label, k in (("spec", 3), ("plain", 0)):
        with Gateway(
            model, params, max_slots=4, spec_k=k, spec_drafter="ngram"
        ) as gw:
            client = GatewayClient(gw.endpoint)
            outs = [
                client.generate(
                    p, 6, seed=seed + i, temperature=0.8, top_k=6
                )
                for i, p in enumerate(prompts)
            ]
            assert all(
                not o.get("shed") and not o.get("error") for o in outs
            )
            results[label] = [o["tokens"] for o in outs]
            if k:
                assert gw.scheduler.audit() == []
    assert results["spec"] == results["plain"]


def test_gateway_spec_decode_trunk_drafter_token_identical(swarm):
    model, params = swarm
    prompts = [REPETITIVE, [4, 5, 6]]
    results = {}
    for label, k in (("spec", 3), ("plain", 0)):
        with Gateway(
            model, params, max_slots=4, spec_k=k, spec_drafter="trunk"
        ) as gw:
            client = GatewayClient(gw.endpoint)
            outs = [client.generate(p, 6) for p in prompts]
            assert all(
                not o.get("shed") and not o.get("error") for o in outs
            )
            results[label] = [o["tokens"] for o in outs]
    assert results["spec"] == results["plain"]


def test_gateway_spec_acceptance_counters_make_sense(swarm):
    model, params = swarm
    with Gateway(
        model, params, max_slots=2, spec_k=4, spec_drafter="ngram"
    ) as gw:
        client = GatewayClient(gw.endpoint)
        out = client.generate(REPETITIVE, 7)
        assert not out.get("error") and len(out["tokens"]) == 7
        s = gw.scheduler.stats()
        assert s["spec_k"] == 4
        assert 0 <= s["spec_accepted_total"] <= s["spec_proposed_total"]
        assert s["spec_tokens_total"] >= s["spec_accepted_total"]
        # the whole point: fewer rounds than tokens on a repetitive
        # stream the drafter can copy
        assert s["spec_rounds_total"] < s["spec_tokens_total"]
        assert 0.0 <= s["spec_acceptance_rate"] <= 1.0
        assert s["spec_effective_k"] >= 1.0


def test_gen_submit_rejects_hostile_sampling_fields(swarm):
    model, params = swarm
    from learning_at_home_tpu.utils.connection import RemoteCallError

    with Gateway(model, params, max_slots=2) as gw:
        client = GatewayClient(gw.endpoint)
        for bad in (
            {"temperature": float("nan")},
            {"temperature": -1.0},
            {"temperature": True},
            {"top_p": 0.0},
            {"top_p": 2.0},
            {"top_k": -3},
            {"top_k": 1.5},
            {"seed": -7},
            {"seed": "abc"},
        ):
            meta = {"prompt": [1, 2, 3], "max_new_tokens": 2, **bad}
            with pytest.raises(RemoteCallError):
                client._rpc("gen_submit", meta)
        # a clean sampled stream still serves after the rejects
        out = client.generate([1, 2, 3], 3, seed=5, temperature=0.7)
        assert not out.get("error") and len(out["tokens"]) == 3
        assert gw.scheduler.streams_errored_total == 0


# ---------------------------------------------------------------------------
# lah_top speculation panel
# ---------------------------------------------------------------------------


def test_lah_top_speculation_panel():
    import importlib

    lah_top = importlib.import_module("tools.lah_top")

    def row(peer_id, gateway_section):
        return {
            "peer_id": peer_id, "role": "gateway",
            "endpoint": ("127.0.0.1", 1), "expires_at": 0.0,
            "snapshot": {"gateway": gateway_section, "metrics": {}},
        }

    rows = [
        row("gw-spec", {
            "streams_active": 1, "streams_total": 9, "slots": 4,
            "slots_in_use": 2, "shed_total": 0, "spec_k": 4,
            "spec_acceptance_rate": 0.71, "spec_effective_k": 2.9,
            "spec_rounds_total": 55, "spec_draft_seconds_total": 0.2,
            "spec_verify_seconds_total": 1.8,
        }),
        # spec-off and malformed gateways get NO panel row, never a crash
        row("gw-off", {"slots": 4, "spec_k": 0}),
        row("gw-bool", {"slots": 4, "spec_k": True}),
        row("gw-junk", {"slots": 4, "spec_k": "four"}),
    ]
    out = lah_top.render(rows, "swarm", dead=set())
    assert "SPECULATION" in out
    panel = out.split("SPECULATION")[1]
    line = next(
        ln for ln in panel.splitlines() if ln.strip().startswith("gw-spec")
    )
    assert "71.0%" in line and "2.90" in line and "55" in line
    assert "10.0%" in line  # draft share: 0.2 / (0.2 + 1.8)
    for peer in ("gw-off", "gw-bool", "gw-junk"):
        assert peer not in panel
    # no speculative gateway anywhere -> no panel at all
    out = lah_top.render(rows[1:], "swarm", dead=set())
    assert "SPECULATION" not in out
