"""Declarative SLO engine + flight recorder + lah_top fleet panels
(ISSUE 19).

The contracts under test:

- **thresholds**: one comparison engine for every report gate — dotted
  lookup, fail-closed on missing/non-numeric metrics, violation dicts
  carrying the offending value;
- **burn rates**: the multiwindow state machine walks OK → WARN → PAGE
  on a virtual clock (PAGE needs BOTH windows burning, WARN fires on the
  slow window alone, recovery returns to OK), transitions land in the
  flight recorder, and entering PAGE writes a parseable on-disk
  artifact;
- **flight recorder**: rings are bounded per component, the component
  set is bounded with an overflow bucket, dumps are throttled per
  reason, and ``/debug/flight`` serves the rings as JSON;
- **lah_top**: fleet quantiles come from merged per-peer sketches
  (tagged ``sketch+MAX`` when coverage is partial), SLO rows parse the
  ``lah_slo_*`` series, and malformed peer sections render dashes —
  never a crash.
"""

import json
import os
import sys

import pytest

from learning_at_home_tpu.utils import flight as flight_mod
from learning_at_home_tpu.utils import slo as slo_mod
from learning_at_home_tpu.utils.flight import FlightRecorder
from learning_at_home_tpu.utils.metrics import (
    MetricsHTTPServer,
    MetricsRegistry,
)
from learning_at_home_tpu.utils.sketch import QuantileSketch
from learning_at_home_tpu.utils.slo import (
    OK,
    PAGE,
    WARN,
    BurnRateSLO,
    SLOEvaluator,
    Threshold,
    evaluate_thresholds,
    lookup,
)

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))

import lah_top  # noqa: E402


# ---------------------------------------------------------------------------
# threshold specs
# ---------------------------------------------------------------------------


def test_thresholds_pass_fail_and_dotted_lookup():
    report = {"serving": {"ttft_p99_ms": 120.0, "completed": 40}}
    assert lookup(report, "serving.ttft_p99_ms") == 120.0
    assert lookup(report, "serving.nope") is None
    assert lookup(report, "serving.ttft_p99_ms.deeper") is None
    specs = [
        Threshold("ttft_ceiling", "serving.ttft_p99_ms", "<=", 200.0),
        Threshold("completed_floor", "serving.completed", ">=", 100.0),
    ]
    violations = evaluate_thresholds(report, specs)
    assert [v["slo"] for v in violations] == ["completed_floor"]
    assert violations[0]["value"] == 40.0
    assert "40" in violations[0]["detail"]


def test_thresholds_fail_closed_on_missing_or_non_numeric():
    specs = [Threshold("ceiling", "a.b", "<=", 1.0)]
    for report in ({}, {"a": {}}, {"a": {"b": "fast"}}, {"a": {"b": None}}):
        violations = evaluate_thresholds(report, specs)
        assert len(violations) == 1 and violations[0]["value"] is None
        assert "missing or non-numeric" in violations[0]["detail"]


def test_threshold_unknown_op_rejected_at_construction():
    with pytest.raises(ValueError):
        Threshold("bad", "a", "!=", 1.0)


def test_burn_rate_slo_spec_validation():
    with pytest.raises(ValueError):
        BurnRateSLO("x", objective=1.0)
    with pytest.raises(ValueError):
        BurnRateSLO("x", objective=0.99, fast_window_s=600, slow_window_s=60)


# ---------------------------------------------------------------------------
# burn-rate state machine (virtual clock via the _monotonic seam)
# ---------------------------------------------------------------------------


def test_burn_rate_walks_ok_warn_ok_page_and_dumps_on_page(
    monkeypatch, tmp_path
):
    clk = {"t": 1000.0}
    monkeypatch.setattr(slo_mod, "_monotonic", lambda: clk["t"])
    monkeypatch.setenv("LAH_FLIGHT_DIR", str(tmp_path))
    flight_mod.recorder.clear()  # drop prior rings + dump throttle state

    counters = {"good": 0.0, "bad": 0.0}
    ev = SLOEvaluator(component="slo-test")
    slo = BurnRateSLO(
        "test_ttft", objective=0.99,
        fast_window_s=60.0, slow_window_s=600.0,
        page_burn=14.0, warn_burn=3.0,
    )
    ev.register(slo, lambda: (counters["good"], counters["bad"]))

    # healthy traffic → OK, zero burn
    clk["t"] = 1010.0
    counters["good"] = 1000.0
    st = ev.evaluate()["test_ttft"]
    assert st["state"] == OK and st["fast_burn"] == 0.0

    # a modest bad burst: slow-window burn 3.85 ≥ warn, < page → WARN
    clk["t"] = 1020.0
    counters["bad"] = 40.0
    st = ev.evaluate()["test_ttft"]
    assert st["state"] == WARN
    assert 3.0 <= st["slow_burn"] < 14.0

    # the burst ages out of the fast window and good traffic dilutes the
    # slow one → recovery to OK (alerts must clear, not latch)
    clk["t"] = 1090.0
    counters["good"] = 3000.0
    st = ev.evaluate()["test_ttft"]
    assert st["state"] == OK
    assert st["fast_burn"] == 0.0

    # a sustained storm burns BOTH windows past page_burn → PAGE
    clk["t"] = 1100.0
    counters["bad"] = 3000.0
    st = ev.evaluate()["test_ttft"]
    assert st["state"] == PAGE
    assert st["fast_burn"] >= 14.0 and st["slow_burn"] >= 14.0
    assert ev.states() == {"test_ttft": PAGE}

    # every transition is on the flight record, in order
    ring = flight_mod.recorder.snapshot()["components"]["slo-test"]
    hops = [
        (e["prev"], e["state"]) for e in ring
        if e["kind"] == "slo_state_change"
    ]
    assert hops == [(OK, WARN), (WARN, OK), (OK, PAGE)]

    # entering PAGE dumped a parseable artifact into LAH_FLIGHT_DIR
    artifacts = [p for p in os.listdir(tmp_path) if p.endswith(".json")]
    assert len(artifacts) == 1 and "slo_page_test_ttft" in artifacts[0]
    doc = json.loads((tmp_path / artifacts[0]).read_text())
    assert doc["reason"] == "slo_page_test_ttft"
    assert any(
        e["kind"] == "slo_state_change"
        for e in doc["components"]["slo-test"]
    )

    # the registry-collector form exports the paged state as lah_slo_*
    series = ev.collect()
    assert series["lah_slo_test_ttft_state"] == 2.0
    assert series["lah_slo_test_ttft_objective"] == 0.99
    assert series["lah_slo_test_ttft_bad_events_total"] == 3000.0
    flight_mod.recorder.clear()


def test_burn_rate_ring_stays_bounded(monkeypatch):
    clk = {"t": 0.0}
    monkeypatch.setattr(slo_mod, "_monotonic", lambda: clk["t"])
    ev = SLOEvaluator(component="slo-bound")
    ev.register(
        BurnRateSLO("tiny", objective=0.9, fast_window_s=1, slow_window_s=5),
        lambda: (clk["t"], 0.0),
    )
    for i in range(2000):
        clk["t"] = float(i) * 0.001  # all samples inside the slow window
        ev.evaluate()
    with ev._lock:
        ring = ev._entries["tiny"][2]
    assert len(ring) <= SLOEvaluator._MAX_SAMPLES


def test_broken_source_is_skipped_not_fatal():
    ev = SLOEvaluator(component="slo-broken")

    def boom():
        raise RuntimeError("counter backend gone")

    ev.register(BurnRateSLO("gone", objective=0.99), boom)
    assert ev.evaluate() == {}  # skipped, evaluator survives


# ---------------------------------------------------------------------------
# flight recorder bounds, throttle, route
# ---------------------------------------------------------------------------


def test_flight_ring_bounded_per_component():
    rec = FlightRecorder(capacity=8)
    for i in range(50):
        rec.record("gateway", "shed", seq=i)
    snap = rec.snapshot()
    ring = snap["components"]["gateway"]
    assert len(ring) == 8
    assert [e["seq"] for e in ring] == list(range(42, 50))  # newest kept
    assert snap["events_total"] == 50


def test_flight_component_set_bounded_with_overflow_bucket():
    rec = FlightRecorder(capacity=4)
    for i in range(flight_mod.MAX_COMPONENTS + 5):
        rec.record(f"comp{i}", "tick")
    snap = rec.snapshot()
    assert len(snap["components"]) <= flight_mod.MAX_COMPONENTS + 1
    assert snap["dropped_components"] == 5
    assert len(snap["components"]["overflow"]) == 4  # capped too


def test_flight_dump_throttles_per_reason_and_clear_resets(
    monkeypatch, tmp_path
):
    clk = {"t": 100.0}
    monkeypatch.setattr(flight_mod, "_monotonic", lambda: clk["t"])
    monkeypatch.setenv("LAH_FLIGHT_DIR", str(tmp_path))
    rec = FlightRecorder(capacity=8)
    rec.record("server", "drain_transition", state="DRAINING")
    p1 = rec.dump("watchdog")
    assert p1 is not None and json.loads(open(p1).read())["reason"] == (
        "watchdog"
    )
    assert rec.dump("watchdog") is None  # throttled
    assert rec.dump("sanitizer_violation") is not None  # distinct reason
    clk["t"] += flight_mod.DUMP_MIN_INTERVAL_S + 1
    assert rec.dump("watchdog") is not None  # throttle window elapsed
    rec.clear()
    assert rec.dump("watchdog") is not None  # clear resets throttle
    assert rec.snapshot()["events_total"] == 0


def test_flight_dump_io_failure_returns_none():
    rec = FlightRecorder()
    assert rec.dump("x", path="/nonexistent-dir/nope/flight.json") is None


def test_debug_flight_route_serves_rings_as_json():
    flight_mod.recorder.clear()
    flight_mod.record("gateway", "preempt", sid="s-1", tokens_redone=3)
    try:
        srv = MetricsHTTPServer(registry=MetricsRegistry(), meta={})
        status, ctype, body = srv._route("/debug/flight")
        assert status == 200 and ctype == "application/json"
        doc = json.loads(body)
        assert doc["components"]["gateway"][0]["kind"] == "preempt"
        assert doc["components"]["gateway"][0]["sid"] == "s-1"
    finally:
        flight_mod.recorder.clear()


def test_flight_metrics_ride_the_default_registry():
    from learning_at_home_tpu.utils.metrics import registry

    flight_mod.recorder.clear()
    try:
        flight_mod.record("client", "hedge_fire", primary="a", backup="b")
        collected = registry.collect()
        assert collected["lah_flight_events_total"] >= 1.0
        assert "lah_flight_dumps_total" in collected
    finally:
        flight_mod.recorder.clear()


# ---------------------------------------------------------------------------
# lah_top fleet panels
# ---------------------------------------------------------------------------


def _peer(peer_id, snapshot, role="gateway"):
    return {
        "peer_id": peer_id, "role": role, "endpoint": "127.0.0.1:0",
        "expires_at": 0.0, "snapshot": snapshot,
    }


def _hist_snapshot(values, with_sketch=True):
    sk = QuantileSketch()
    for v in values:
        sk.add(v)
    out = {"count": len(values), "sum": sum(values), "buckets": {}}
    if with_sketch:
        out["sketch"] = sk.to_dict()
    return out


def test_fleet_latency_rows_merge_true_quantiles_across_peers():
    rows = [
        _peer("gw-slow", {"metrics": {"histograms": {
            "lah_x_seconds": _hist_snapshot([1.0] * 5),
        }}}),
        _peer("gw-fast", {"metrics": {"histograms": {
            "lah_x_seconds": _hist_snapshot([0.001] * 995),
        }}}),
    ]
    (row,) = lah_top.fleet_latency_rows(rows)
    assert row["name"] == "lah_x_seconds"
    assert row["source"] == "sketch" and row["count"] == 1000
    # the true fleet p99 is the fast mass — the very number the MAX
    # fallback gets 1000× wrong
    assert abs(row["p99"] - 0.001) <= 0.001 * 0.011


def test_fleet_latency_rows_partial_coverage_tags_max_fallback():
    rows = [
        _peer("gw-new", {"metrics": {"histograms": {
            "lah_x_seconds": _hist_snapshot([0.5] * 10),
        }}}),
        _peer("gw-old", {"metrics": {"histograms": {
            "lah_x_seconds": _hist_snapshot([0.5] * 10, with_sketch=False),
        }}}),
    ]
    (row,) = lah_top.fleet_latency_rows(rows)
    assert row["source"] == "sketch+MAX"
    assert row["count"] == 20  # counts still cover everyone


def test_fleet_latency_rows_malformed_sections_render_dashes():
    rows = [
        _peer("dead", None),
        _peer("junk1", {"metrics": "nope"}),
        _peer("junk2", {"metrics": {"histograms": [1, 2]}}),
        _peer("junk3", {"metrics": {"histograms": {
            "lah_x_seconds": {
                "count": 3, "sketch": {"kind": "garbled"},
            },
        }}}),
    ]
    (row,) = lah_top.fleet_latency_rows(rows)
    assert row["source"] == "-"
    assert row["p50"] is None and row["p99"] is None
    assert lah_top._q_ms(row["p99"]) == "-"
    assert lah_top._q_ms(0.25) == "250.00"


def test_fleet_latency_rows_labeled_histograms_merge_all_variants():
    rows = [_peer("gw", {"metrics": {"histograms": {
        "lah_x_seconds": {
            '{"pool": "a"}': _hist_snapshot([0.1] * 4),
            '{"pool": "b"}': _hist_snapshot([0.2] * 4),
        },
    }}})]
    (row,) = lah_top.fleet_latency_rows(rows)
    assert row["count"] == 8 and row["source"] == "sketch"
    assert row["p50"] is not None


def test_slo_rows_parse_series_and_tolerate_junk():
    rows = [
        _peer("gw-1", {"metrics": {"collected": {
            "lah_slo_gateway_ttft_state": 2.0,
            "lah_slo_gateway_ttft_fast_burn": 21.5,
            "lah_slo_gateway_ttft_slow_burn": 16.0,
            "lah_slo_gateway_ttft_objective": 0.99,
        }}}),
        _peer("gw-2", {"metrics": {"collected": {
            "lah_slo_gateway_ttft_state": "paged",  # malformed value
            "lah_server_jobs_processed_total": 9.0,  # not an SLO series
        }}}),
        _peer("dead", None),
    ]
    out = lah_top.slo_rows(rows)
    assert len(out) == 2
    assert out[0] == {
        "peer_id": "gw-1", "slo": "gateway_ttft", "state": "PAGE",
        "fast_burn": 21.5, "slow_burn": 16.0, "objective": 0.99,
    }
    assert out[1]["peer_id"] == "gw-2" and out[1]["state"] == "-"


def test_render_includes_fleet_and_slo_panels_never_crashes_on_junk():
    rows = [
        _peer("gw-1", {"metrics": {
            "collected": {
                "lah_slo_gateway_ttft_state": 1.0,
                "lah_slo_gateway_ttft_fast_burn": 4.0,
                "lah_slo_gateway_ttft_slow_burn": 3.5,
                "lah_slo_gateway_ttft_objective": 0.99,
            },
            "histograms": {
                "lah_gateway_ttft_seconds": _hist_snapshot([0.05] * 20),
            },
        }}),
        _peer("junk", {"metrics": {"histograms": 3, "collected": []}}),
        _peer("dead", None),
    ]
    text = lah_top.render(rows, prefix="t", dead={"gone"})
    assert "FLEET LATENCY" in text
    assert "lah_gateway_ttft_seconds" in text
    assert "SLO" in text and "WARN" in text
    assert "gone" in text  # dead peers still listed
