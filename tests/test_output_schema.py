"""Server-published output schemas (info RPC) driving client specs.

Round-1 deferral: clients needed a hand-written ``output_spec_fn`` for any
expert whose output is not shaped like its first input.  The server now
records per-leaf output shapes at warmup / first forward and publishes
them in ``info``; RemoteExpert builds io_callback result specs from that —
including **multi-output** experts (reference contract: experts are
arbitrary modules, SURVEY.md §2 RemoteExpert row).
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from learning_at_home_tpu.client import RemoteExpert, reset_client_rpc
from learning_at_home_tpu.server import ExpertBackend, Server

HID = 12
STATS = 3


def _make_backend(warm: bool):
    def apply_fn(params, x):
        y = jnp.tanh(x @ params["w"])
        stats = y[:, :STATS] * params["gain"]
        return y, stats  # two outputs, second NOT input-shaped

    params = {
        "w": jax.random.normal(jax.random.PRNGKey(0), (HID, HID)) * 0.3,
        "gain": jnp.float32(2.0),
    }
    backend = ExpertBackend(
        "multi.0", apply_fn, params, optax.sgd(0.01), max_batch_size=64
    )
    if warm:
        backend.warmup([np.zeros((4, HID), np.float32)], buckets=[4, 64])
    return backend


@pytest.fixture(scope="module")
def schema_server():
    server = Server({"multi.0": _make_backend(warm=True)}, host="127.0.0.1")
    server.run_in_background()
    yield server
    server.shutdown()
    reset_client_rpc()


def test_info_publishes_output_schema(schema_server):
    expert = RemoteExpert("multi.0", schema_server.endpoint)
    schema = expert.info()["output_schema"]
    assert schema == [
        {"shape": [HID], "dtype": "float32"},
        {"shape": [STATS], "dtype": "float32"},
    ]


def test_multi_output_forward_and_grad_without_spec_fn(schema_server):
    """No output_spec_fn anywhere: the published schema drives the client."""
    expert = RemoteExpert("multi.0", schema_server.endpoint)
    state = schema_server.experts["multi.0"].state_dict()["params"]
    rs = np.random.RandomState(1)
    x = jnp.asarray(rs.randn(4, HID).astype(np.float32))

    y, stats = expert(x)
    y_exp = np.tanh(np.asarray(x) @ state["w"])
    np.testing.assert_allclose(np.asarray(y), y_exp, atol=1e-5)
    np.testing.assert_allclose(
        np.asarray(stats), y_exp[:, :STATS] * state["gain"], atol=1e-5
    )

    # grads flow through BOTH outputs' cotangents, under jit
    def loss(x):
        y, stats = expert(x)
        return jnp.sum(y**2) + jnp.sum(stats)

    g = jax.jit(jax.grad(loss))(x)

    def local_loss(x):
        y = jnp.tanh(x @ state["w"])
        return jnp.sum(y**2) + jnp.sum(y[:, :STATS] * state["gain"])

    g_exp = jax.grad(local_loss)(x)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_exp), atol=1e-4)


def test_unwarmed_multi_output_fails_loudly():
    """Without warmup there is no schema yet; the fallback spec (first
    input) mismatches a 2-output expert and must raise, not misbind."""
    server = Server({"multi.0": _make_backend(warm=False)}, host="127.0.0.1")
    server.run_in_background()
    try:
        expert = RemoteExpert("multi.0", server.endpoint)
        x = jnp.ones((4, HID), jnp.float32)
        with pytest.raises(Exception, match="returned 2 outputs"):
            expert(x)
    finally:
        server.shutdown()
        reset_client_rpc()
