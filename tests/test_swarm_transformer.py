"""Swarm-mode DMoE-Transformer integration (the reference's headline
trainer) + the async data-parallel contract: several independent trainers
sharing one expert pool, each expert updating asynchronously."""

import concurrent.futures as cf

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from learning_at_home_tpu.client import reset_client_rpc
from learning_at_home_tpu.client.routing import StaticExpertSource
from learning_at_home_tpu.models import make_expert
from learning_at_home_tpu.models.transformer_swarm import (
    SwarmDMoETransformerLM,
    SwarmTransformerConfig,
)
from learning_at_home_tpu.server import ExpertBackend, Server

D = 16
VOCAB = 64


@pytest.fixture(scope="module")
def swarm():
    """Expert server in a SEPARATE process (the real deployment topology).

    In-process client+server share one XLA CPU runtime: a trainer's
    io_callback blocks an execution slot while waiting for the reply, and
    the server's jitted backward needs a slot from the same pool — under
    enough concurrency that converges to a stall (observed via
    faulthandler stack dumps).  Cross-process, each side owns its runtime.
    """
    import os
    import subprocess
    import sys
    import time

    from learning_at_home_tpu.client import RemoteExpert

    from learning_at_home_tpu.utils.subproc import clean_jax_subprocess_env

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = clean_jax_subprocess_env(repo)
    port = 43311
    proc = subprocess.Popen(
        [
            sys.executable, "-m", "learning_at_home_tpu.server",
            "--num-experts", "2", "--hidden-dim", str(D),
            "--expert-prefix", "ffn0", "--port", str(port), "--no-dht",
            "--optimizer", "adam", "--lr", "1e-3",
            "--max-batch-size", "2048", "--warmup", "32", "64",
        ],
        env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
    )
    uids = ["ffn0.0", "ffn0.1"]
    endpoint = ("127.0.0.1", port)
    probe = RemoteExpert(uids[0], endpoint, timeout=10.0)
    deadline = time.time() + 120
    up = False
    while time.time() < deadline:
        if proc.poll() is not None:
            raise AssertionError(f"server died: {proc.stdout.read()[-2000:]}")
        try:
            probe.info()
            up = True
            break
        except Exception:
            time.sleep(1.0)
    assert up, "server never came up"
    source = StaticExpertSource({uid: endpoint for uid in uids})

    class ExpertView:
        """update-count telemetry via the info RPC (server is remote now)."""

        @property
        def experts(self):
            return {
                uid: RemoteExpert(uid, endpoint, timeout=10.0).info()
                for uid in uids
            }

    yield ExpertView(), source
    proc.terminate()
    proc.wait(timeout=30)
    reset_client_rpc()


def _model(source):
    # deliberately tiny: every first-time XLA compile happens inside an RPC
    # window on a 1-core box, so the compile budget must stay small
    cfg = SwarmTransformerConfig(
        vocab_size=VOCAB, d_model=D, n_layers=1, n_heads=4, seq_len=8,
        grid_size=(2,), k_best=2,
        # 1-core CI: concurrent trainers serialize many first-time compiles
        # through one runtime thread
        forward_timeout=240.0, backward_timeout=240.0,
    )
    return SwarmDMoETransformerLM(cfg, source)


def test_swarm_transformer_trains(swarm):
    server, source = swarm
    model = _model(source)
    params = model.init_params(jax.random.PRNGKey(0))
    opt = optax.adamw(3e-3)
    opt_state = opt.init(params)
    step = model.make_train_step(opt)

    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, VOCAB, (4, 8)))
    tgt = jnp.asarray(rs.randint(0, VOCAB, (4, 8)))
    losses = []
    for _ in range(6):
        params, opt_state, loss = step(params, opt_state, ids, tgt)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    # expert-side async updates happened (one per layer-MoE backward per step)
    total_updates = sum(i["update_count"] for i in server.experts.values())
    assert total_updates > 0


def test_async_dp_multiple_trainers(swarm):
    """SURVEY §2.2 DP contract: independent trainers, no barrier, shared
    experts updating on arrival.  Both trainers must complete and the
    expert pool must absorb updates from both."""
    server, source = swarm
    before = sum(i["update_count"] for i in server.experts.values())

    class Trainer:
        def __init__(self, seed):
            self.model = _model(source)
            self.params = self.model.init_params(jax.random.PRNGKey(seed))
            self.opt = optax.adamw(1e-3)
            self.opt_state = self.opt.init(self.params)
            self.step = self.model.make_train_step(self.opt)
            self.rs = np.random.RandomState(seed)
            self.losses = []

        def run_steps(self, n):
            for _ in range(n):
                # same shape as test 1 → server buckets already compiled
                ids = jnp.asarray(self.rs.randint(0, VOCAB, (4, 8)))
                tgt = jnp.asarray(self.rs.randint(0, VOCAB, (4, 8)))
                self.params, self.opt_state, loss = self.step(
                    self.params, self.opt_state, ids, tgt
                )
                self.losses.append(float(loss))

    trainers = [Trainer(1), Trainer(2)]
    # warm each trainer's own trace serially (1-core CI: concurrent
    # first-time traces + server compiles starve the RPC deadlines;
    # the CONTRACT under test is concurrent steady-state training)
    for t in trainers:
        t.run_steps(1)
    with cf.ThreadPoolExecutor(2) as pool:
        list(pool.map(lambda t: t.run_steps(2), trainers))
    for t in trainers:
        assert len(t.losses) == 3 and np.isfinite(t.losses).all()
    after = sum(i["update_count"] for i in server.experts.values())
    # 2 trainers x 3 steps x 1 layer, each backward updating >= 1 expert
    assert after - before >= 6


def test_pipelined_trainer_converges_and_counts():
    """PipelinedSwarmTrainer: concurrent workers consume exactly `steps`
    micro-batches, updates land under the apply lock, loss decreases.
    (Network-free: a local quadratic model stands in for the swarm LM.)"""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import optax

    from learning_at_home_tpu.client.trainer import PipelinedSwarmTrainer

    class Toy:
        def loss_fn(self, params, x, y):
            return ((x @ params["w"] - y) ** 2).mean()

    rs = np.random.RandomState(0)
    w_true = rs.randn(8, 1).astype(np.float32)
    params = {"w": jnp.zeros((8, 1))}
    xs = rs.randn(64, 8).astype(np.float32)
    ys = xs @ w_true

    def batches():
        while True:
            i = rs.randint(0, 48)
            yield jnp.asarray(xs[i : i + 16]), jnp.asarray(ys[i : i + 16])

    trainer = PipelinedSwarmTrainer(Toy(), optax.sgd(0.05), params, n_workers=3)
    summary = trainer.train(batches(), steps=40, tokens_per_batch=16)
    assert trainer.step_count == 40
    assert summary["steps"] == 40
    assert summary["final_loss"] < trainer.losses[0] * 0.1
    assert np.isfinite(np.asarray(trainer.params["w"])).all()
