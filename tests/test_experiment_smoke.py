"""Benchmarks-as-tests (SURVEY §4): each experiment CLI must run end to end
at tiny scale and emit its JSON — guards the scripts against bitrot."""

import json
import math
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_script(args, timeout=240):
    from learning_at_home_tpu.utils.subproc import clean_jax_subprocess_env

    env = clean_jax_subprocess_env(REPO)
    proc = subprocess.run(
        [sys.executable, *args],
        env=env,
        capture_output=True,
        text=True,
        timeout=timeout,
        cwd=REPO,
    )
    assert proc.returncode == 0, proc.stdout[-1500:] + proc.stderr[-1500:]
    return [
        json.loads(line)
        for line in proc.stdout.splitlines()
        if line.startswith("{")
    ]


@pytest.mark.slow
def test_benchmark_dht_smoke():
    (out,) = run_script(
        ["experiments/benchmark_dht.py", "--nodes", "3", "--ops", "8"]
    )
    assert out["hit_rate"] == 1.0
    assert out["store_ops_per_sec"] > 0


@pytest.mark.slow
def test_benchmark_throughput_smoke():
    (out,) = run_script(
        [
            "experiments/benchmark_throughput.py",
            "--num-experts", "1", "--clients", "2", "--requests", "2",
            "--hidden-dim", "16", "--rows", "4",
        ]
    )
    assert out["samples_per_sec"] > 0
    assert out["batches_formed"] >= 1


@pytest.mark.slow
def test_mnist_expert_smoke():
    lines = run_script(
        [
            "experiments/mnist_expert.py",
            "--steps", "6", "--hidden-dim", "32", "--batch-size", "32",
        ]
    )
    assert lines[-1]["updates_applied"] == 6
    assert lines[-1]["steps_per_sec"] > 0


@pytest.mark.slow
def test_train_lm_pod_smoke():
    lines = run_script(
        [
            "experiments/train_lm.py", "--mode", "pod", "--steps", "3",
            "--num-experts", "8", "--batch-size", "8", "--d-model", "32",
            "--seq-len", "16", "--log-every", "2",
        ],
        timeout=300,
    )
    assert lines and all("loss" in l for l in lines)


@pytest.mark.slow
def test_train_lm_swarm_subprocess_smoke():
    """The headline decentralized trainer, against REAL server processes."""
    lines = run_script(
        [
            "experiments/train_lm.py", "--mode", "swarm",
            "--subprocess-servers", "--steps", "3",
            "--experts-per-layer", "2", "--n-servers", "1",
            "--n-layers", "1", "--batch-size", "2", "--d-model", "16",
            "--seq-len", "8", "--log-every", "2",
            # no --base-port: servers bind ephemeral ports and publish the
            # real endpoint via the DHT (fixed ports collided with orphans
            # from killed prior runs — VERDICT.md r5)
        ],
        timeout=420,
    )
    assert lines and all("loss" in l for l in lines)


@pytest.mark.slow
def test_train_lm_overlap_loss_parity_smoke():
    """ISSUE 9 satellite (the PR 7 leftover): ``--overlap`` drives the
    ScMoE shortcut schedule in train_lm; its loss curve must match the
    serial arm (``--overlap-serial`` — same primitive ops, join-early
    scheduling) on identical seeds/servers/data.  The schedules are
    bitwise-comparable in one process (tests/test_overlap.py); across
    two fresh swarm runs the curves must still agree to float tolerance
    (each run's servers start from the same crc32-seeded experts)."""
    common = [
        "experiments/train_lm.py", "--mode", "swarm",
        "--steps", "4", "--experts-per-layer", "2", "--n-servers", "1",
        "--n-layers", "1", "--batch-size", "2", "--d-model", "16",
        "--seq-len", "8", "--log-every", "1", "--seed", "3",
    ]
    losses = {}
    for arm in ("--overlap", "--overlap-serial"):
        lines = run_script(common + [arm], timeout=420)
        losses[arm] = [l["loss"] for l in lines if "loss" in l]
    assert losses["--overlap"], "overlapped arm produced no loss curve"
    assert len(losses["--overlap"]) == len(losses["--overlap-serial"])
    import numpy as np

    np.testing.assert_allclose(
        losses["--overlap"], losses["--overlap-serial"], atol=1e-4,
    )


@pytest.mark.slow
def test_generate_lm_smoke():
    outs = run_script(
        ["experiments/generate_lm.py", "--no-checkpoint",
         "--num-experts", "8", "--d-model", "64", "--seq-len", "64",
         "--prompt", "ab", "--max-new-tokens", "6", "--bench", "8"],
        timeout=300,
    )
    comp = next(o for o in outs if "completion" in o)
    bench = next(o for o in outs if "decode_steps_per_sec" in o)
    assert comp["completion"].startswith("ab")
    assert bench["decode_steps_per_sec"] > 0 and bench["use_cache"]


@pytest.mark.slow
def test_decode_gap_eval_smoke():
    (out,) = run_script(
        ["experiments/decode_gap_eval.py", "--steps", "6",
         "--eval-batches", "2", "--batch-size", "8", "--seq-len", "32",
         "--d-model", "32", "--num-experts", "8", "--skip-control"],
        timeout=300,
    )
    assert out["gating"] == "expert_choice"
    assert out["eval_ce_training_routing"] > 0
    assert "decode_gap_nats" in out


@pytest.mark.slow
def test_train_lm_multi_trainer_averaging_convergence(tmp_path):
    """ISSUE 3 acceptance: with ``--averaging`` on, a 2-trainer swarm
    smoke ends with trunk+gate parameters EQUAL across trainers.

    Each trainer runs one blocking mid-run round (step 3) plus the final
    round after its last step, then dumps its final params to
    ``avg_final_params.npz``; the trees must agree to atol=1e-6 (they are
    in fact bitwise equal: the final partition bytes come from one
    reduction per partition, distributed verbatim)."""
    import numpy as np

    lines = run_script(
        [
            "experiments/train_lm.py", "--mode", "swarm",
            "--n-trainers", "2", "--steps", "6",
            "--experts-per-layer", "2", "--n-servers", "1",
            "--n-layers", "1", "--batch-size", "2", "--d-model", "16",
            "--seq-len", "8", "--log-every", "2", "--lr", "0.005",
            "--averaging", "--averaging-every", "3",
            "--checkpoint-dir", str(tmp_path),
        ],
        timeout=600,
    )
    summary = next(l for l in lines if "n_trainers" in l)
    for t in summary["trainers"]:
        assert t["averaging_rounds"] == 2, t  # step-3 round + final round
        assert t["averaging_degraded_rounds"] == 0, t
    a = np.load(tmp_path / "t0" / "avg_final_params.npz")
    b = np.load(tmp_path / "t1" / "avg_final_params.npz")
    assert set(a.files) == set(b.files) and len(a.files) > 0
    for key in a.files:
        np.testing.assert_allclose(a[key], b[key], atol=1e-6)


@pytest.mark.slow
def test_train_lm_multi_trainer_async_dp():
    """Concurrent multi-trainer async DP (SURVEY §2.2 DP: "many independent
    trainers" against one shared expert pool; round-4 verdict task 3).

    Two trainer PROCESSES — own trunks/gates/optimizers, disjoint corpus
    shards — train against the same subprocess expert servers + DHT.  The
    contract under true write contention: both loss curves fall, numerics
    stay finite, and the client/server ledger closes — the servers' summed
    ``update_count`` cannot exceed the trainers' total SENT backward RPCs
    (each update executes ≥1 sent task; pools may merge concurrent
    trainers' rows into one padded batch = one optimizer step; acked is
    NOT the bound — a post-quorum straggler cancelled client-side still
    executes server-side) yet must exceed what either trainer alone sent
    (both trainers' gradients were applied)."""
    lines = run_script(
        [
            "experiments/train_lm.py", "--mode", "swarm",
            "--n-trainers", "2", "--steps", "16",
            "--experts-per-layer", "4", "--n-servers", "2",
            "--n-layers", "1", "--batch-size", "2", "--d-model", "32",
            "--seq-len", "16", "--log-every", "1", "--lr", "0.005",
            # no --base-port: ephemeral server ports (the port-collision
            # flake this test was known for — VERDICT.md r5)
        ],
        timeout=600,
    )
    summary = next(l for l in lines if "n_trainers" in l)
    assert summary["n_trainers"] == 2
    for t in summary["trainers"]:
        # measured drop is ~2.3 nats in 16 steps; 0.5 leaves 4x margin for
        # async-interleaving nondeterminism (no wall-clock dependence)
        assert t["final_loss"] < t["first_loss"] - 0.5, t
        assert math.isfinite(t["final_loss"]), t
        assert t["backward_rpcs_ok"] > 0, t
        assert t["backward_rpcs_sent"] >= t["backward_rpcs_ok"], t
    total_sent = summary["backward_rpcs_sent_total"]
    updates = summary["server_updates_total"]
    max_single = max(t["backward_rpcs_sent"] for t in summary["trainers"])
    assert 0 < updates <= total_sent, summary
    assert updates > max_single, summary  # both trainers' work was applied
    assert summary["experts_updated"] >= 3, summary  # load spread over grid


@pytest.mark.slow
def test_train_lm_swarm_blockq8_loss_parity():
    """Quality parity gate (ISSUE 5): a short swarm run whose dispatch
    wire is pinned to ``blockq8`` must track the uncompressed run's loss
    curve within the run-to-run band this smoke class tolerates (same
    seed, same data; async interleaving is the residual noise source).
    Guards against a quantizer that silently degrades training while
    every per-RPC check still passes."""
    base = [
        "experiments/train_lm.py", "--mode", "swarm",
        "--subprocess-servers", "--steps", "10",
        "--experts-per-layer", "2", "--n-servers", "1",
        "--n-layers", "1", "--batch-size", "2", "--d-model", "32",
        "--seq-len", "16", "--log-every", "1", "--lr", "0.005",
        "--seed", "0",
    ]
    curves = {}
    for codec in ("none", "blockq8"):
        lines = run_script(
            base + (["--wire-codec", codec] if codec != "none" else []),
            timeout=420,
        )
        losses = [l["loss"] for l in lines if "loss" in l]
        assert losses and all(math.isfinite(v) for v in losses), lines
        curves[codec] = losses
    # both curves fall, and the quantized endpoint sits inside the band
    # the async-dp smoke uses for run-to-run drift (0.5 nats)
    for codec, losses in curves.items():
        assert losses[-1] < losses[0], (codec, losses)
    assert abs(curves["blockq8"][-1] - curves["none"][-1]) <= 0.5, curves
