"""Full-stack integration: Server ⇄ DHT ⇄ RemoteMixtureOfExperts.

The complete call stack of SURVEY.md §3.1/§3.3: server declares its experts
to the DHT (heartbeat), client discovers alive experts via the DHT and
routes batches; record expiry drops dead servers from routing."""

import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from learning_at_home_tpu.client import reset_client_rpc
from learning_at_home_tpu.client.moe import MoEDispatchError, RemoteMixtureOfExperts
from learning_at_home_tpu.dht import DHT
from learning_at_home_tpu.server.server import background_server

HID = 16


def test_server_dht_moe_end_to_end():
    bootstrap = DHT()
    client_dht = DHT(initial_peers=[bootstrap.endpoint])
    try:
        with background_server(
            num_experts=4,
            hidden_dim=HID,
            expert_prefix="ffn",
            seed=3,
            dht=DHT(initial_peers=[bootstrap.endpoint]),
            update_period=0.5,
        ) as (endpoint, srv):
            # wait for the first heartbeat to land
            deadline = time.time() + 10
            while time.time() < deadline:
                alive = client_dht._loop.run(client_dht._get_alive("ffn"))
                if len(alive) == 4:
                    break
                time.sleep(0.1)
            assert len(alive) == 4, f"experts never appeared in DHT: {alive}"
            assert all(ep == endpoint for ep in alive.values())

            # route a real batch through DHT discovery
            moe = RemoteMixtureOfExperts(
                in_features=HID, grid_size=(4,), uid_prefix="ffn",
                source=client_dht, k_best=2, k_min=1, alive_ttl=0.2,
            )
            gate = moe.init_gate_params(jax.random.PRNGKey(0))
            x = jnp.asarray(np.random.RandomState(0).randn(3, HID).astype(np.float32))
            out = moe(x, gate)
            assert out.shape == (3, HID)
            assert np.isfinite(np.asarray(out)).all()

            # gradients flow end-to-end through DHT-discovered experts
            g = jax.grad(lambda gp, x: jnp.sum(moe(x, gp) ** 2))(gate, x)
            assert float(jnp.abs(g["w0"]).sum()) > 0
            srv.dht.shutdown()

        # server down: records expire (TTL = 2*update_period = 1s)
        deadline = time.time() + 15
        while time.time() < deadline:
            alive = client_dht._loop.run(client_dht._get_alive("ffn"))
            if not alive:
                break
            time.sleep(0.2)
        assert alive == {}, f"dead server's records never expired: {alive}"

        # routing now fails loudly, not silently
        moe2 = RemoteMixtureOfExperts(
            in_features=HID, grid_size=(4,), uid_prefix="ffn",
            source=client_dht, k_best=2, k_min=1, alive_ttl=0.0,
        )
        gate2 = moe2.init_gate_params(jax.random.PRNGKey(1))
        with pytest.raises(Exception):
            np.asarray(moe2(jnp.ones((2, HID), jnp.float32), gate2))
    finally:
        client_dht.shutdown()
        bootstrap.shutdown()
        reset_client_rpc()


def test_native_transport_parity():
    """The C++ framepump data plane (transport='native') serves the same
    protocol: forward/backward replies match the asyncio transport
    numerically, and wrong requests still error cleanly."""
    import numpy as np
    import pytest

    from learning_at_home_tpu.client import RemoteExpert, reset_client_rpc
    from learning_at_home_tpu.native import native_available
    from learning_at_home_tpu.utils.connection import RemoteCallError

    if not native_available():
        pytest.skip("native framepump unavailable (no g++?)")

    rs = np.random.RandomState(0)
    x = rs.randn(3, 16).astype(np.float32)
    g = rs.randn(3, 16).astype(np.float32)
    outs = {}
    for transport in ("asyncio", "native"):
        with background_server(
            num_experts=1, hidden_dim=16, expert_prefix="nt", seed=3,
            transport=transport,
        ) as (endpoint, srv):
            e = RemoteExpert("nt.0", endpoint)
            fwd = e.forward_blocking([x])[0]
            bwd = e.backward_blocking([x], [g])[0]
            info = e.info()
            assert info["n_inputs"] == 1
            with pytest.raises(RemoteCallError, match="unknown expert"):
                RemoteExpert("nt.999", endpoint).forward_blocking([x])
            outs[transport] = (np.asarray(fwd), np.asarray(bwd))
        reset_client_rpc()
    np.testing.assert_allclose(outs["native"][0], outs["asyncio"][0], atol=1e-5)
    np.testing.assert_allclose(outs["native"][1], outs["asyncio"][1], atol=1e-5)


def test_native_transport_pipelined_ordering():
    """A client that pipelines several requests on ONE connection must get
    replies in request order — the native plane chains per-connection
    dispatches, it does not rely on clients being one-in-flight."""
    import socket
    import struct

    import numpy as np
    import pytest

    from learning_at_home_tpu.native import native_available
    from learning_at_home_tpu.utils.serialization import pack_message, unpack_message

    if not native_available():
        pytest.skip("native framepump unavailable")

    with background_server(
        num_experts=4, hidden_dim=8, expert_prefix="ord", seed=4,
        transport="native",
    ) as (endpoint, srv):
        s = socket.create_connection(endpoint)
        # pipeline 8 info requests for DIFFERENT uids without reading
        uids = [f"ord.{i % 4}" for i in range(8)]
        for uid in uids:
            payload = pack_message("info", (), {"uid": uid})
            s.sendall(struct.pack("<I", len(payload)) + payload)
        for uid in uids:
            (ln,) = struct.unpack("<I", s.recv(4, socket.MSG_WAITALL))
            buf = b""
            while len(buf) < ln:
                buf += s.recv(ln - len(buf))
            msg_type, _, meta = unpack_message(buf)
            assert msg_type == "result"
            assert meta["name"] == uid, (meta["name"], uid)
        s.close()


def test_native_transport_wire_dtype():
    """bf16 wire compression through the C++ framepump plane: the native
    dispatcher routes the same meta, so wire-compressed requests must get
    wire-compressed replies with f32-grade numerics."""
    import numpy as np
    import pytest

    from learning_at_home_tpu.client import RemoteExpert, reset_client_rpc
    from learning_at_home_tpu.native import native_available

    if not native_available():
        pytest.skip("native framepump unavailable (no g++?)")

    rs = np.random.RandomState(1)
    x = rs.randn(4, 16).astype(np.float32)
    with background_server(
        num_experts=1, hidden_dim=16, expert_prefix="nw", seed=5,
        transport="native",
    ) as (endpoint, srv):
        e32 = RemoteExpert("nw.0", endpoint)
        e16 = RemoteExpert("nw.0", endpoint, wire_dtype="bfloat16")
        y32 = np.asarray(e32.forward_blocking([x])[0])
        reply = e16.forward_blocking([x])[0]
        assert reply.dtype == np.dtype("bfloat16")
        np.testing.assert_allclose(
            np.asarray(reply, np.float32), y32, rtol=0.05, atol=0.05
        )
    reset_client_rpc()
