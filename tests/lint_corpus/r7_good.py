"""R7 corpus: coroutines properly scheduled (must be clean)."""
import asyncio


async def refresh():
    return 1


class Node:
    async def heartbeat(self):
        return 2

    async def tick(self):
        await self.heartbeat()


async def main():
    task = asyncio.ensure_future(refresh())
    await task


class Watcher:
    async def poll(self):
        return 3


def poll():
    return "sync module-level poll, unrelated to Watcher.poll"


def caller():
    poll()  # sync call: a class's same-named coroutine must not flag this
