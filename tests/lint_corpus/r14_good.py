"""R14 corpus: feature-gated wire forms emitted correctly (must be
clean) — the dict codec form behind ``pool.supports("codec")`` with the
legacy-string fallback, a reply echoing its request's ``rid`` parameter,
and a mux sender whose rid comes from ``mux.next_rid()``."""


async def send_encoded(pool, wire_obj, wmeta, tensors):
    meta = {"uid": "ffn.0"}
    if pool.supports("codec"):
        meta["wire"] = wmeta
    else:
        meta["wire"] = "bfloat16"
    return await pool.rpc_prepared("forward", wire_obj, meta)


def reply(msg_type, wire, rid=None):
    return pack_frames(msg_type, wire, {"ok": "y"}, rid=rid)  # noqa: F821


def mux_send(mux, msg_type, wire, meta):
    rid = mux.next_rid()
    return pack_frames(msg_type, wire, meta, rid=rid)  # noqa: F821
