"""Suppression corpus: a real finding, baselined (must exit clean)."""
import time


async def slow_probe():
    # deliberate: one-shot startup probe on a private loop, nothing else
    # is scheduled yet
    time.sleep(0.01)  # lah-lint: ignore[R1]

    # standalone-comment form, with explanation lines after the marker:
    # lah-lint: ignore[R1] startup-only, loop serves nothing yet
    # (the annotation covers the next code line, through this comment)
    time.sleep(0.01)
