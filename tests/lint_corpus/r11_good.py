"""R11 corpus: the lock-acquiring helper on the hot path carries its
own @runs_on assertion (must be clean)."""
from learning_at_home_tpu.utils import sanitizer

_lock = sanitizer.lock("client.rpc.state")


@sanitizer.runs_on("host", site="corpus.r11.helper")
def _mutate_registry():
    with _lock:
        return 1


@sanitizer.runs_on("host", site="corpus.r11.hot_path")
def hot_path():
    return _mutate_registry()
