"""R13 corpus: the handler hard-requires ``meta["uid"]`` and every
sender construction path guarantees it (must be clean)."""


class _Handler:
    def _dispatch(self, payload, rid=None):
        msg_type, tensors, meta = unpack_message(payload)  # noqa: F821
        if msg_type == "forward":
            uid = meta["uid"]
            wire = meta.get("wire")
            trace = meta.get("trace")
            return uid, wire, trace
        return None


async def send(pool, tensors, tag=None):
    meta = {"uid": "ffn.0", "wire": "bfloat16", "trace": "t0"}
    return await pool.rpc("forward", tensors, meta)
