"""R7 corpus: coroutines called but never awaited (must fire)."""


async def refresh():
    return 1


class Node:
    async def heartbeat(self):
        return 2

    def tick(self):
        self.heartbeat()  # never scheduled


def main():
    refresh()  # never scheduled
