"""R2 corpus: blocking future waits that can self-deadlock (fire)."""
import asyncio


async def waits_on_future(fut):
    return fut.result()  # blocks the loop; await it instead


async def bridges_to_other_loop(client_loop, coro):
    return client_loop().run(coro)  # loop blocking on a loop


def chains_threadsafe(coro, loop):
    return asyncio.run_coroutine_threadsafe(coro, loop).result()
