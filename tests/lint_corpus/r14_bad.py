"""R14 corpus: feature-gated wire forms emitted without their
negotiation guard (must fire twice) — the dict ``wire`` codec form
without a dominating ``pool.supports("codec")`` test, and a rid-tagged
frame built from a literal instead of the rid-echo/next_rid idioms."""


async def send_encoded(pool, wire_obj, wmeta, tensors):
    meta = {"uid": "ffn.0"}
    if wmeta is not None:
        meta["wire"] = wmeta
    return await pool.rpc_prepared("forward", wire_obj, meta)


def frame(msg_type, wire):
    return pack_frames(  # noqa: F821
        msg_type, wire, {"uid": "ffn.0"}, rid=7
    )
