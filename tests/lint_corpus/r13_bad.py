"""R13 corpus: the handler hard-requires ``meta["uid"]`` but one sender
construction path only sets it conditionally (must fire) — the exact
shape of a retry/fallback branch dropping a field an old handler still
subscripts."""


class _Handler:
    def _dispatch(self, payload, rid=None):
        msg_type, tensors, meta = unpack_message(payload)  # noqa: F821
        if msg_type == "forward":
            uid = meta["uid"]
            wire = meta.get("wire")
            trace = meta.get("trace")
            return uid, wire, trace
        return None


async def send(pool, tensors, tag=None):
    meta = {"wire": "bfloat16", "trace": "t0"}
    if tag is not None:
        meta["uid"] = tag
    return await pool.rpc("forward", tensors, meta)
