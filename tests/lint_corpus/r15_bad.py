"""R15 corpus: the handler parses a meta field
(``zzz_unfielded``) that PROTOCOL.md's machine-read field rows do not
document for the op (must fire).  The doc corpus is the real repo
docs/, resolved by walking up from this file."""


class _Handler:
    def _dispatch(self, payload, rid=None):
        msg_type, tensors, meta = unpack_message(payload)  # noqa: F821
        if msg_type == "forward":
            uid = meta.get("uid")
            zzz = meta.get("zzz_unfielded")
            return uid, zzz
        return None
