"""R4 corpus: held-reply module without require_v2 (must fire)."""
from learning_at_home_tpu.utils.connection import PoolRegistry


class Averager:
    PART_MSG = "avg_part"  # held-reply protocol marker

    def __init__(self):
        self.registry = PoolRegistry()  # held replies starve v1 pools
