"""R1 corpus: the same work, legally placed (must be clean)."""
import asyncio
import time
from learning_at_home_tpu.utils.serialization import WireTensors, pack_message


def host_side(payload):
    time.sleep(0.1)  # sync function: not loop-hosted
    return WireTensors.prepare([payload]), pack_message("r", [payload])


async def handler(lock):
    await asyncio.sleep(0.1)  # async sleep yields the loop
    empty = WireTensors.prepare()  # zero-arg prepare: no payload walk
    async with lock:
        pass
    return empty
