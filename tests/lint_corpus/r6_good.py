"""R6 corpus: narrow or logged handlers (must be clean)."""
import logging

logger = logging.getLogger(__name__)


def narrow(fn):
    try:
        fn()
    except (OSError, RuntimeError):
        pass  # narrow types: a deliberate, scoped ignore


def logged(fn):
    try:
        fn()
    except Exception:
        logger.exception("fn failed")
