"""R5 corpus: string keys everywhere (must be clean)."""
import msgpack

from learning_at_home_tpu.utils.serialization import pack_message


def stats_reply(bucket, count):
    return pack_message("stats", [], {str(bucket): count})


def raw_pack():
    return msgpack.packb({"one": 1})
