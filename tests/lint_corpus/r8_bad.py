"""R8 corpus: a server-side dispatcher handling a wire op that no
PROTOCOL.md op table documents (must fire).  The doc corpus is the real
repo docs/, resolved by walking up from this file."""


async def _dispatch(msg_type, meta, tensors):
    if msg_type == "forward":
        return {"ok": True}
    if msg_type == "zz_undocumented_op":  # not in any PROTOCOL.md table
        return {"ok": True}
    raise ValueError(f"unknown op {msg_type}")
