"""R9 corpus: a headline metric literal missing from the
OBSERVABILITY.md catalog (must fire)."""


def collect() -> dict:
    return {"lah_zz_bogus_widget_total": 1}
