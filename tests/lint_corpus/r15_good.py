"""R15 corpus: the handler parses exactly the fields PROTOCOL.md's
machine-read rows document for ``forward`` — ``uid`` plus the
family-common ``wire``/``trace`` (must be clean)."""


class _Handler:
    def _dispatch(self, payload, rid=None):
        msg_type, tensors, meta = unpack_message(payload)  # noqa: F821
        if msg_type == "forward":
            uid = meta.get("uid")
            wire = meta.get("wire")
            trace = meta.get("trace")
            return uid, wire, trace
        return None
