"""R4 corpus: held-reply module pinning v2 (must be clean)."""
from learning_at_home_tpu.utils.connection import PoolRegistry


class Averager:
    PART_MSG = "avg_part"

    def __init__(self):
        self.registry = PoolRegistry(require_v2=True)
