"""R9 corpus: catalogued metric names only (must be clean) — both are
verbatim OBSERVABILITY.md entries."""


def collect() -> dict:
    return {
        "lah_gateway_kv_pages_total": 4,
        "lah_server_draining": 0,
    }
