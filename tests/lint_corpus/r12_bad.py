"""R12 corpus: the sender emits a meta field (``bogus``) that no
handler of the op parses (must fire).  Both sides of the wire contract
live in this file so the schema extractor sees handler and sender."""


class _Handler:
    def _dispatch(self, payload, rid=None):
        msg_type, tensors, meta = unpack_message(payload)  # noqa: F821
        if msg_type == "forward":
            uid = meta.get("uid")
            wire = meta.get("wire")
            trace = meta.get("trace")
            return uid, wire, trace
        return None


async def send(pool, tensors):
    return await pool.rpc(
        "forward", tensors, {"uid": "ffn.0", "bogus": 1}
    )
