"""R6 corpus: bare/swallowed excepts (must fire)."""


def swallow_everything(fn):
    try:
        fn()
    except:  # noqa: E722
        print("oops")


def swallow_silently(fn):
    try:
        fn()
    except Exception:
        pass
