"""R1 corpus: blocking calls inside async defs (must fire)."""
import time
from learning_at_home_tpu.utils.serialization import WireTensors, pack_message


async def handler(payload):
    time.sleep(0.1)  # blocking sleep on the loop
    data = open("/tmp/x").read()  # file I/O on the loop
    wire = WireTensors.prepare([payload])  # payload prepare on the loop
    return pack_message("r", [payload]), data, wire
