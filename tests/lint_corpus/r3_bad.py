"""R3 corpus: fan-out constant >= mux in-flight limit (must fire)."""
MAX_CHUNKS_PER_PART = 80  # held replies: needs to sit BELOW max_inflight


class Pool:
    def __init__(self, endpoint, max_inflight: int = 64):
        self.endpoint = endpoint
        self.max_inflight = max_inflight
