"""R12 corpus: every sender-emitted meta field is parsed by the op's
handler (must be clean)."""


class _Handler:
    def _dispatch(self, payload, rid=None):
        msg_type, tensors, meta = unpack_message(payload)  # noqa: F821
        if msg_type == "forward":
            uid = meta.get("uid")
            wire = meta.get("wire")
            trace = meta.get("trace")
            return uid, wire, trace
        return None


async def send(pool, tensors):
    return await pool.rpc("forward", tensors, {"uid": "ffn.0"})
