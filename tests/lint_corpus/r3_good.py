"""R3 corpus: constant safely below the limit (must be clean)."""
MAX_CHUNKS_PER_PART = 48


class Pool:
    def __init__(self, endpoint, max_inflight: int = 64):
        self.endpoint = endpoint
        self.max_inflight = max_inflight
