"""R11 corpus: an @runs_on hot path calls a helper that acquires a
tracked lock without carrying its own @runs_on assertion (must fire)."""
from learning_at_home_tpu.utils import sanitizer

_lock = sanitizer.lock("client.rpc.state")


def _mutate_registry():
    with _lock:
        return 1


@sanitizer.runs_on("host", site="corpus.r11.hot_path")
def hot_path():
    return _mutate_registry()
