"""R10 corpus: one tracked lock with no CONCURRENCY.md rank row, plus a
rank-inverted nesting of two documented locks (must fire twice)."""
from learning_at_home_tpu.utils import sanitizer

_rogue = sanitizer.lock("zz.not.in.the.table")


def inverted():
    # trainer.apply (rank 30) held while taking moe.sessions (rank 25):
    # ranks must strictly increase inward
    with sanitizer.lock("trainer.apply"):
        with sanitizer.lock("moe.sessions"):
            return 1
