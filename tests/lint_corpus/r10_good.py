"""R10 corpus: documented lock names nested in increasing rank order
(must be clean)."""
from learning_at_home_tpu.utils import sanitizer


class Registry:
    def __init__(self):
        self._lock = sanitizer.lock("client.rpc.state")

    def snapshot(self):
        # client.rpc.state (rank 10) -> moe.sessions (rank 25): inward
        # ranks strictly increase
        with self._lock:
            with sanitizer.lock("moe.sessions"):
                return 1
