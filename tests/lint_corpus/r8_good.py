"""R8 corpus: every handled wire op is documented (must be clean) —
``forward``/``info`` sit in PROTOCOL.md op tables, ``hello`` is the
prose-documented handshake."""


async def _dispatch(msg_type, meta, tensors):
    if msg_type == "hello":
        return {"ok": True}
    if msg_type in ("forward", "info"):
        return {"ok": True}
    raise ValueError(f"unknown op {msg_type}")
