"""R2 corpus: the legal shapes (must be clean)."""
import asyncio


async def awaits_future(fut):
    return await fut


def host_submit(coro, loop):
    fut = asyncio.run_coroutine_threadsafe(coro, loop)
    return fut  # caller decides where to wait (BackgroundLoop.run guards)
