"""R5 corpus: non-string msgpack meta keys (must fire)."""
import msgpack

from learning_at_home_tpu.utils.serialization import pack_message


def stats_reply(bucket, count):
    # int bucket keys round-trip through msgpack but broke the stats
    # consumers once already (PR 1)
    return pack_message("stats", [], {64: count, "nested": {128: 1}})


def raw_pack():
    return msgpack.packb({1: "one"})
