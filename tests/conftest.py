"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

The sandbox's sitecustomize imports jax and registers the axon TPU PJRT
plugin in EVERY interpreter, with ``JAX_PLATFORMS=axon`` preset in the
environment — so by the time this conftest runs, jax is already imported and
env-var edits alone are ineffective.  We therefore both set the env vars
(for any subprocesses we spawn) and update the live jax config (for this
process).  CPU is required here: the client tests use host callbacks
(``io_callback``), which the axon plugin does not implement, and the
multi-device sharding tests need the 8 virtual CPU devices
(SURVEY.md §4 "TPU-build implication").
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
# Concurrency sanitizer (ISSUE 6) ON BY DEFAULT under pytest: every
# tier-1 dispatch runs with thread-identity assertions, the event-loop
# stall detector, and lock-order tracking armed.  Must be set before the
# package is imported (the arming decision is made at import time);
# LAH_SANITIZE=0 in the environment opts a run out.
os.environ.setdefault("LAH_SANITIZE", "1")
os.environ.pop("PALLAS_AXON_POOL_IPS", None)  # skip axon register() in subprocesses
xla_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402  (already imported by sitecustomize; config still mutable)

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# opt-in hang diagnosis: dump all thread stacks periodically
if os.environ.get("LAH_DUMP_STACKS"):
    import faulthandler

    faulthandler.dump_traceback_later(
        int(os.environ["LAH_DUMP_STACKS"]), repeat=True, exit=False
    )

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _sanitizer_guard():
    """The shared replacement for the old per-file thread-tracking
    monkeypatch fixtures (ISSUE 6): every test runs under the sanitizer's
    thread-identity checks, and any violation it records FAILS the test
    that caused it.  Seeded-violation tests drain their expected findings
    through ``sanitizer.expect_violations()`` so this guard stays green.
    """
    from learning_at_home_tpu.utils import sanitizer

    if not sanitizer.enabled():
        yield
        return
    before = sanitizer.violation_count()
    yield
    new = sanitizer.violations()[before:]
    if new:
        rendered = "\n".join(
            f"  [{v['kind']}] {v['site']} on thread {v['thread']}: "
            f"{v['detail']}"
            for v in new
        )
        pytest.fail(
            f"concurrency sanitizer recorded {len(new)} violation(s) "
            f"during this test:\n{rendered}"
        )


def pytest_sessionfinish(session, exitstatus):
    """Export the sanitizer roll-up into the gate output: printed on
    every run (the tier-1 log IS the gate artifact) and written as JSON
    when LAH_SANITIZE_SUMMARY names a path (tools/collect_gate)."""
    try:
        from learning_at_home_tpu.utils import sanitizer
    except Exception:
        return
    if not sanitizer.enabled():
        return
    import json

    summary = sanitizer.summary()
    stall = sanitizer.stall_stats()
    if stall.get("last"):
        # the live stack was already logged when the stall fired; the
        # one-line gate summary keeps only what/how-long
        stall["last"] = {
            k: v for k, v in stall["last"].items() if k != "stack"
        }
    summary.update(stall=stall)
    line = json.dumps(summary, sort_keys=True)
    print(f"\nLAH_SANITIZER_SUMMARY {line}")
    path = os.environ.get("LAH_SANITIZE_SUMMARY")
    if path:
        try:
            with open(path, "w") as fh:
                fh.write(line + "\n")
        except OSError:
            pass
