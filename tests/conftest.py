"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

Must run before the first ``import jax`` anywhere in the test process so the
multi-device sharding paths (all_to_all expert dispatch, pjit) are exercised
without TPU hardware — SURVEY.md §4 "TPU-build implication".
"""

import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
xla_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
