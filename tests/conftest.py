"""Test configuration: force JAX onto a virtual 8-device CPU mesh.

The sandbox's sitecustomize imports jax and registers the axon TPU PJRT
plugin in EVERY interpreter, with ``JAX_PLATFORMS=axon`` preset in the
environment — so by the time this conftest runs, jax is already imported and
env-var edits alone are ineffective.  We therefore both set the env vars
(for any subprocesses we spawn) and update the live jax config (for this
process).  CPU is required here: the client tests use host callbacks
(``io_callback``), which the axon plugin does not implement, and the
multi-device sharding tests need the 8 virtual CPU devices
(SURVEY.md §4 "TPU-build implication").
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)  # skip axon register() in subprocesses
xla_flags = os.environ.get("XLA_FLAGS", "")
if "--xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402  (already imported by sitecustomize; config still mutable)

jax.config.update("jax_platforms", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# opt-in hang diagnosis: dump all thread stacks periodically
if os.environ.get("LAH_DUMP_STACKS"):
    import faulthandler

    faulthandler.dump_traceback_later(
        int(os.environ["LAH_DUMP_STACKS"]), repeat=True, exit=False
    )
