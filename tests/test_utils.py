"""Unit tests for L1 utilities: wire format, nested structures, timed storage.

Mirrors the reference's pure unit-test tier (SURVEY.md §4: serializer
round-trip, nested flatten/pack)."""

import asyncio

import numpy as np
import pytest

from learning_at_home_tpu.utils.nested import (
    nested_compare,
    nested_flatten,
    nested_pack,
    nested_structure,
)
from learning_at_home_tpu.utils.serialization import (
    MSGPackSerializer,
    pack_message,
    recv_frame,
    send_frame,
    unpack_message,
)
from learning_at_home_tpu.utils.connection import (
    ConnectionPool,
    PoolRegistry,
    RemoteCallError,
)
from learning_at_home_tpu.utils.timed_storage import TimedStorage, get_dht_time


def test_pack_unpack_roundtrip():
    tensors = [
        np.random.randn(3, 4).astype(np.float32),
        np.arange(7, dtype=np.int32),
        np.random.randn(2, 2, 2).astype(np.float64),
        np.array(3.5, dtype=np.float32),  # scalar
    ]
    meta = {"uid": "ffn.0.1", "k": 2, "nested": {"a": [1, 2]}}
    payload = pack_message("forward", tensors, meta)
    msg_type, out, out_meta = unpack_message(payload)
    assert msg_type == "forward"
    assert out_meta == meta
    assert len(out) == len(tensors)
    for a, b in zip(tensors, out):
        assert a.dtype == b.dtype and a.shape == b.shape
        np.testing.assert_array_equal(a, b)


def test_pack_bfloat16():
    import ml_dtypes

    t = np.arange(8, dtype=np.float32).astype(ml_dtypes.bfloat16)
    _, (out,), _ = unpack_message(pack_message("fwd", [t]))
    assert out.dtype == ml_dtypes.bfloat16
    np.testing.assert_array_equal(np.asarray(t, np.float32), np.asarray(out, np.float32))


def test_pack_jax_arrays():
    import jax.numpy as jnp

    t = jnp.ones((4, 4), jnp.bfloat16) * 2
    _, (out,), _ = unpack_message(pack_message("fwd", [t]))
    assert out.shape == (4, 4)
    np.testing.assert_array_equal(np.asarray(t, np.float32), np.asarray(out, np.float32))


def test_msgpack_serializer():
    obj = {"experts": ["ffn.0", "ffn.1"], "endpoint": ["1.2.3.4", 8080], "x": 1.5}
    assert MSGPackSerializer.loads(MSGPackSerializer.dumps(obj)) == obj


def test_frame_send_recv():
    async def run():
        server_got = []

        async def handler(reader, writer):
            server_got.append(await recv_frame(reader))
            await send_frame(writer, b"pong" * 1000)
            writer.close()

        server = await asyncio.start_server(handler, "127.0.0.1", 0)
        port = server.sockets[0].getsockname()[1]
        reader, writer = await asyncio.open_connection("127.0.0.1", port)
        await send_frame(writer, b"ping" * 5000)
        reply = await recv_frame(reader)
        writer.close()
        server.close()
        await server.wait_closed()
        assert server_got == [b"ping" * 5000]
        assert reply == b"pong" * 1000

    asyncio.run(run())


def test_nested_roundtrip():
    tree = {"a": np.ones(3), "b": (np.zeros(2), [np.array(1.0), {"c": np.array(2)}])}
    leaves = nested_flatten(tree)
    assert len(leaves) == 4
    rebuilt = nested_pack(leaves, nested_structure(tree))
    assert nested_compare(tree, rebuilt)
    rebuilt2 = nested_pack(leaves, tree)  # example-tree form
    assert nested_compare(tree, rebuilt2)


def test_timed_storage_expiry(monkeypatch):
    now = [1000.0]
    monkeypatch.setattr(
        "learning_at_home_tpu.utils.timed_storage.get_dht_time", lambda: now[0]
    )
    store = TimedStorage()
    assert store.store("k", "v1", 1010.0)
    assert not store.store("k", "v0", 1005.0)  # older expiration loses
    assert store.get("k") == ("v1", 1010.0)
    now[0] = 1011.0
    assert store.get("k") is None
    assert len(store) == 0


def test_timed_storage_maxsize(monkeypatch):
    now = [0.0]
    monkeypatch.setattr(
        "learning_at_home_tpu.utils.timed_storage.get_dht_time", lambda: now[0]
    )
    store = TimedStorage(maxsize=2)
    store.store("a", 1, 10.0)
    store.store("b", 2, 20.0)
    store.store("c", 3, 30.0)
    assert len(store) == 2
    assert store.get("a") is None  # earliest-expiring evicted
    assert store.get("b") and store.get("c")


def test_run_forever_restarts():
    import threading
    import time as _time

    from learning_at_home_tpu.utils.asyncio_utils import run_forever

    calls = []
    done = threading.Event()

    def flaky():
        calls.append(1)
        if len(calls) >= 3:
            done.set()
        raise RuntimeError("boom")

    thread, stop = run_forever(flaky)
    assert done.wait(timeout=10), f"only {len(calls)} calls"
    stop.set()
    thread.join(timeout=5)
    assert not thread.is_alive()


def test_unpack_fuzz_never_hangs_or_corrupts():
    """Random mutations of a valid payload must either parse or raise a
    clean error — never crash, hang, or return tensors inconsistent with
    their declared shape (wire robustness against bit rot / malice).
    Covers BOTH frame flavors: plain v1 payloads and protocol-v2
    request-id-tagged frames (``pack_frames`` with rid), whose headers
    must also peek cleanly or raise."""
    import random

    from learning_at_home_tpu.utils.serialization import (
        WireTensors,
        pack_frames,
        peek_header,
    )

    rng = random.Random(0)
    tensors_in = [np.ones((4, 8), np.float32), np.arange(6, dtype=np.int32)]
    meta_in = {"uid": "f.1", "n_inputs": 2}
    v1 = bytearray(pack_message("forward", tensors_in, meta_in))
    v2 = bytearray(
        b"".join(
            bytes(p)
            for p in pack_frames(
                "forward", WireTensors.prepare(tensors_in), meta_in, rid=1234
            )
        )[4:]  # strip the outer length prefix: fuzz the payload like v1
    )
    for trial in range(300):
        base = v1 if trial % 2 == 0 else v2
        buf = bytearray(base)
        for _ in range(rng.randint(1, 8)):
            buf[rng.randrange(len(buf))] = rng.randrange(256)
        try:
            peek_header(bytes(buf))  # parse-or-raise, never hang/crash
        except Exception:
            pass
        try:
            msg_type, tensors, meta = unpack_message(bytes(buf))
        except Exception:
            continue  # clean rejection is fine
        # real invariants for accepted payloads: all tensor data lies
        # within the frame, and parse → re-serialize → parse is stable
        assert sum(t.nbytes for t in tensors) <= len(buf)
        msg2, tensors2, meta2 = unpack_message(
            pack_message(msg_type, tensors, meta)
        )
        assert msg2 == msg_type and meta2 == meta
        for a, b in zip(tensors, tensors2):
            assert a.dtype == b.dtype and a.shape == b.shape
            np.testing.assert_array_equal(a, b)


class TestRttEma:
    """Latency-EMA hygiene (round-4 review): the signal that drives
    latency-aware routing must not be poisoned by fast failures."""

    def _run(self, coro):
        return asyncio.run(coro)

    def test_error_replies_do_not_update_ema(self):
        """Error exchanges are typically the fastest (no expert compute);
        counting them would steer selection TOWARD broken peers."""

        async def main():
            async def handler(reader, writer):
                while True:
                    try:
                        await recv_frame(reader)
                    except Exception:
                        break
                    await send_frame(
                        writer, pack_message("error", meta={"message": "boom"})
                    )

            server = await asyncio.start_server(handler, "127.0.0.1", 0)
            ep = server.sockets[0].getsockname()[:2]
            pool = ConnectionPool(ep)
            for _ in range(3):
                try:
                    await pool.rpc("forward", (), {"uid": "x"}, timeout=5)
                except RemoteCallError:
                    pass
            assert pool.rtt_ema is None  # errors never counted
            pool.close()
            server.close()

        self._run(main())

    def test_timeout_folds_elapsed_into_ema(self):
        """Peers slower than the timeout must still be penalized — the
        whole point of the latency bias."""

        async def main():
            async def handler(reader, writer):
                await asyncio.sleep(30)  # black hole: never reply

            server = await asyncio.start_server(handler, "127.0.0.1", 0)
            ep = server.sockets[0].getsockname()[:2]
            pool = ConnectionPool(ep)
            with pytest.raises(TimeoutError):
                await pool.rpc("forward", (), {"uid": "x"}, timeout=0.3)
            assert pool.rtt_ema is not None and pool.rtt_ema >= 0.25
            pool.close()
            server.close()

        self._run(main())

    def test_registry_peek_is_non_creating(self):
        reg = PoolRegistry()
        assert reg.peek(("127.0.0.1", 1)) is None
        assert len(reg._pools) == 0  # peek must not register pools
        pool = reg.get(("127.0.0.1", 1))
        assert reg.peek(("127.0.0.1", 1)) is pool


def test_unpack_pure_garbage_frames():
    """Arbitrary byte strings (not derived from any valid frame — the
    complement of test_unpack_fuzz_never_hangs_or_corrupts' mutation
    fuzz) must either raise a plain Exception promptly or parse into
    tensors whose bytes stay inside the frame; a hang becomes a loud
    faulthandler abort instead of a silent CI stall."""
    import faulthandler
    import os

    # faulthandler has ONE global dump_traceback_later timer: arming ours
    # would clobber (and the finally would cancel) the session-wide
    # LAH_DUMP_STACKS diagnostic conftest installs — skip the guard when
    # the operator already has hang diagnosis enabled
    own_guard = not os.environ.get("LAH_DUMP_STACKS")
    if own_guard:
        faulthandler.dump_traceback_later(60, exit=True)
    try:
        rs = np.random.RandomState(0)
        for _ in range(300):
            buf = rs.bytes(int(rs.randint(0, 256)))
            try:
                msg_type, tensors, meta = unpack_message(buf)
            except Exception:
                continue  # controlled failure is the contract
            # an ACCEPTED garbage frame must still be internally sound:
            # declared tensor bytes cannot exceed the buffer
            assert sum(t.nbytes for t in tensors) <= len(buf)
            assert isinstance(msg_type, str)
    finally:
        if own_guard:
            faulthandler.cancel_dump_traceback_later()


def test_upcast_from_wire_rejects_dtype_mismatch():
    """A declared wire dtype is a contract: floating payloads of any OTHER
    dtype are a client-side encoding bug and must be rejected, not
    silently laundered into float32 (round-4 advisor)."""
    import ml_dtypes
    import pytest

    from learning_at_home_tpu.server.connection_handler import (
        upcast_from_wire,
    )

    good = np.ones((2, 2), ml_dtypes.bfloat16)
    ints = np.arange(4, dtype=np.int32)
    out = upcast_from_wire([good, ints], "bfloat16")
    assert out[0].dtype == np.float32
    assert out[1].dtype == np.int32  # non-float payloads ride along as-is

    with pytest.raises(ValueError, match="declares wire=bfloat16"):
        upcast_from_wire([np.ones((2, 2), np.float64)], "bfloat16")
    # no declared wire: anything goes, nothing is cast
    passthrough = upcast_from_wire([np.ones(2, np.float64)], None)
    assert passthrough[0].dtype == np.float64
