"""Unit tests for the token-dispatch math (ops/moe_dispatch.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from learning_at_home_tpu.ops import (
    combine_outputs,
    compute_capacity,
    dispatch_tokens,
    top_k_gating,
)


def test_compute_capacity():
    assert compute_capacity(128, 8, 2, 1.0) == 32
    assert compute_capacity(128, 8, 2, 1.25) == 40
    assert compute_capacity(1, 64, 1, 1.0) == 1  # floor of 1


def test_topk_full_capacity_equals_softmax_topk():
    rs = np.random.RandomState(0)
    logits = jnp.asarray(rs.randn(16, 4).astype(np.float32))
    plan = top_k_gating(logits, k=2, capacity=16)
    assert float(plan.dropped_fraction) == 0.0

    gates = np.asarray(jax.nn.softmax(logits, axis=-1))
    weights = np.asarray(plan.combine.sum(axis=2))  # [n, E]
    for b in range(16):
        top2 = np.argsort(-gates[b])[:2]
        expected = gates[b, top2] / gates[b, top2].sum()
        np.testing.assert_allclose(
            np.sort(weights[b][weights[b] > 0]), np.sort(expected), atol=1e-6
        )
        assert set(np.nonzero(weights[b])[0]) == set(top2)


def test_each_slot_used_once():
    rs = np.random.RandomState(1)
    logits = jnp.asarray(rs.randn(64, 8).astype(np.float32))
    plan = top_k_gating(logits, k=2, capacity=8)
    # no expert slot is claimed by two tokens
    slot_usage = np.asarray(plan.dispatch.sum(axis=0))  # [E, C]
    assert slot_usage.max() <= 1


def test_capacity_dropping():
    # all tokens want expert 0 → only C of them fit
    logits = jnp.full((10, 4), -10.0).at[:, 0].set(10.0)
    plan = top_k_gating(logits, k=1, capacity=3)
    kept = np.asarray(plan.dispatch[:, 0].sum(axis=1))  # per-token kept flag
    assert kept.sum() == 3
    # earliest tokens win slots (deterministic token order)
    np.testing.assert_array_equal(kept[:3], 1)
    assert float(plan.dropped_fraction) == pytest.approx(0.7)


def test_dispatch_combine_roundtrip():
    rs = np.random.RandomState(2)
    x = jnp.asarray(rs.randn(32, 8).astype(np.float32))
    logits = jnp.asarray(rs.randn(32, 4).astype(np.float32))
    plan = top_k_gating(logits, k=2, capacity=32)
    buckets = dispatch_tokens(x, plan)  # [E, C, d]
    assert buckets.shape == (4, 32, 8)
    # identity expert: combine(dispatch(x)) == x for weight-1 routing
    plan1 = top_k_gating(logits, k=1, capacity=32)
    y = combine_outputs(dispatch_tokens(x, plan1), plan1)
    np.testing.assert_allclose(np.asarray(y), np.asarray(x), atol=1e-5)


def test_indexed_plan_matches_onehot():
    """Index-form routing must agree with the one-hot reference exactly."""
    from learning_at_home_tpu.ops import (
        combine_outputs_indexed,
        dispatch_tokens_indexed,
        top_k_gating_indices,
    )

    rs = np.random.RandomState(7)
    for n, E, k, cap in [(32, 8, 2, 6), (16, 4, 1, 2), (64, 8, 4, 16)]:
        logits = jnp.asarray(rs.randn(n, E).astype(np.float32))
        x = jnp.asarray(rs.randn(n, 8).astype(np.float32))
        ref = top_k_gating(logits, k, cap)
        idxp = top_k_gating_indices(logits, k, cap)
        np.testing.assert_allclose(
            float(idxp.dropped_fraction), float(ref.dropped_fraction), atol=1e-6
        )
        np.testing.assert_allclose(
            float(idxp.aux_loss), float(ref.aux_loss), atol=1e-5
        )
        buckets_ref = dispatch_tokens(x, ref)
        buckets_idx = dispatch_tokens_indexed(x, idxp)
        np.testing.assert_allclose(
            np.asarray(buckets_idx), np.asarray(buckets_ref), atol=1e-6
        )
        y = jnp.asarray(rs.randn(E, cap, 8).astype(np.float32))
        np.testing.assert_allclose(
            np.asarray(combine_outputs_indexed(y, idxp)),
            np.asarray(combine_outputs(y, ref)),
            atol=1e-5,
        )


def test_indexed_gating_is_differentiable():
    from learning_at_home_tpu.ops import (
        combine_outputs_indexed,
        dispatch_tokens_indexed,
        top_k_gating_indices,
    )

    rs = np.random.RandomState(8)
    x = jnp.asarray(rs.randn(16, 8).astype(np.float32))
    w = jnp.asarray(rs.randn(8, 4).astype(np.float32) * 0.1)

    def loss(w):
        plan = top_k_gating_indices(x @ w, k=2, capacity=8)
        return combine_outputs_indexed(
            dispatch_tokens_indexed(x, plan), plan
        ).sum() + plan.aux_loss

    g = jax.grad(loss)(w)
    assert np.isfinite(np.asarray(g)).all()
    assert float(jnp.abs(g).sum()) > 0


def test_gating_is_differentiable():
    rs = np.random.RandomState(3)
    x = jnp.asarray(rs.randn(16, 8).astype(np.float32))
    w = jnp.asarray(rs.randn(8, 4).astype(np.float32) * 0.1)

    def loss(w):
        plan = top_k_gating(x @ w, k=2, capacity=8)
        return combine_outputs(dispatch_tokens(x, plan), plan).sum() + plan.aux_loss

    g = jax.grad(loss)(w)
    assert np.isfinite(np.asarray(g)).all()
    assert float(jnp.abs(g).sum()) > 0


def test_router_jitter_selection_only():
    """Jitter may change WHICH experts are picked, but the combine weights
    must always be the clean (unjittered) gate values at the selected
    indices — the fixed noise pattern must never bias the output mixture.
    And duplicate rows must stop routing identically."""
    from learning_at_home_tpu.ops.moe_dispatch import top_k_gating_indices

    rs = np.random.RandomState(0)
    # 64 IDENTICAL rows: without jitter they all pick the same experts
    row = rs.randn(1, 16).astype(np.float32) * 0.01
    logits = jnp.asarray(np.repeat(row, 64, axis=0))
    plan_clean = top_k_gating_indices(logits, 2, 8)
    plan_jit = top_k_gating_indices(logits, 2, 8, jitter=0.5)
    # clean: identical rows route identically -> heavy capacity dropping
    assert float(plan_clean.dropped_fraction) > 0.5
    # jittered: selection decorrelates, drop falls sharply
    assert float(plan_jit.dropped_fraction) < ONE_THIRD * float(
        plan_clean.dropped_fraction
    ) + 0.2
    # weights are renormalized CLEAN gate values at the selected experts
    gates = jax.nn.softmax(logits, axis=-1)
    slot = np.asarray(plan_jit.slot_for_token)  # [n, k] flat slots
    w = np.asarray(plan_jit.weights)
    expert_of_slot = slot // 8
    checked = 0
    for i in range(64):
        if (slot[i] < 0).any():
            continue  # dropped choices hide the selected expert id
        sel = expert_of_slot[i]
        gvals = np.asarray(gates[i])[sel]
        expect = gvals / gvals.sum()
        np.testing.assert_allclose(w[i], expect, rtol=1e-5, atol=1e-6)
        checked += 1
    assert checked > 0


ONE_THIRD = 1.0 / 3.0


def test_expert_choice_matches_dense_reference():
    """Expert-choice dispatch+combine must equal the naive computation:
    y[t] = sum over experts that picked t of affinity * expert_out(x[t])."""
    from learning_at_home_tpu.ops.moe_dispatch import (
        combine_outputs_expert_choice,
        dispatch_tokens_expert_choice,
        expert_choice_gating,
    )

    rs = np.random.RandomState(1)
    n, E, C, d = 32, 4, 8, 16
    logits = jnp.asarray(rs.randn(n, E).astype(np.float32))
    x = jnp.asarray(rs.randn(n, d).astype(np.float32))
    plan = expert_choice_gating(logits, C)
    assert plan.token_for_slot.shape == (E, C)
    assert (np.asarray(plan.token_for_slot) >= 0).all()  # always filled

    # fake per-expert transforms: scale by (e+1)
    xs = dispatch_tokens_expert_choice(x, plan)  # [E, C, d]
    ys = xs * (jnp.arange(E, dtype=x.dtype)[:, None, None] + 1)
    y = combine_outputs_expert_choice(ys, plan, n)

    gates = np.asarray(jax.nn.softmax(logits, axis=-1))
    expect = np.zeros((n, d), np.float32)
    tfs = np.asarray(plan.token_for_slot)
    for e in range(E):
        for c in range(C):
            t = tfs[e, c]
            expect[t] += gates[t, e] * (e + 1) * np.asarray(x)[t]
    np.testing.assert_allclose(np.asarray(y), expect, rtol=1e-5, atol=1e-5)

    # uncovered fraction agrees with the scatter count
    covered = np.zeros(n, bool)
    covered[tfs.reshape(-1)] = True
    np.testing.assert_allclose(
        float(plan.uncovered_fraction), 1.0 - covered.mean(), atol=1e-6
    )

    # differentiable end-to-end (weights come from softmax affinities)
    def loss(logits):
        p = expert_choice_gating(logits, C)
        ys = dispatch_tokens_expert_choice(x, p) * 2.0
        return combine_outputs_expert_choice(ys, p, n).sum()

    g = jax.grad(loss)(logits)
    assert np.isfinite(np.asarray(g)).all()
    assert float(jnp.abs(g).sum()) > 0


def test_expert_choice_capacity_clamped_to_token_count():
    """capacity > n must clamp (top_k bound), not crash at trace time."""
    from learning_at_home_tpu.ops.moe_dispatch import expert_choice_gating

    rs = np.random.RandomState(2)
    logits = jnp.asarray(rs.randn(8, 2).astype(np.float32))
    plan = expert_choice_gating(logits, capacity=10)  # 10 > n=8
    assert plan.token_for_slot.shape == (2, 8)
    assert float(plan.uncovered_fraction) == 0.0  # C=n covers everything


class TestFusedAdafactor:
    """Parity of ops.fused_adafactor vs optax.adafactor (the 6-traversal
    chain it replaces — see the module docstring for the measured cost)."""

    def _tree(self, dtype):
        rs = np.random.RandomState(0)
        mk = lambda *s: jnp.asarray(rs.randn(*s).astype(np.float32)).astype(dtype)
        return {
            "w_big": mk(256, 512),      # factored (both dims >= 128)
            "w_small": mk(64, 32),      # 2-D but unfactored (dims < 128)
            "bias": mk(512),            # 1-D: unfactored
            "stack": mk(3, 256, 512),   # 3-D: factors the two largest dims
            "scalar": jnp.asarray(0.5, dtype),
        }

    def _grads(self, dtype, seed):
        rs = np.random.RandomState(seed)
        mk = lambda *s: jnp.asarray(0.1 * rs.randn(*s).astype(np.float32)).astype(dtype)
        return {
            "w_big": mk(256, 512),
            "w_small": mk(64, 32),
            "bias": mk(512),
            "stack": mk(3, 256, 512),
            "scalar": jnp.asarray(0.01, dtype),
        }

    @pytest.mark.parametrize("dtype,tol", [(jnp.float32, 2e-5), (jnp.bfloat16, 2e-2)])
    def test_matches_optax_adafactor(self, dtype, tol):
        import optax

        from learning_at_home_tpu.ops.fused_adafactor import fused_adafactor

        params_ref = self._tree(dtype)
        params_fused = self._tree(dtype)
        ref = optax.adafactor(1e-2)
        fused = fused_adafactor(1e-2)
        s_ref = ref.init(params_ref)
        s_fused = fused.init(params_fused)

        for step in range(5):
            grads = self._grads(dtype, seed=step + 1)
            u_ref, s_ref = ref.update(grads, s_ref, params_ref)
            u_fused, s_fused = fused.update(grads, s_fused, params_fused)
            params_ref = optax.apply_updates(params_ref, u_ref)
            params_fused = optax.apply_updates(params_fused, u_fused)
            for k in params_ref:
                np.testing.assert_allclose(
                    np.asarray(params_fused[k], np.float32),
                    np.asarray(params_ref[k], np.float32),
                    rtol=tol, atol=tol, err_msg=f"step {step} leaf {k}",
                )

    def test_state_layout_matches_for_sharding_and_checkpoint(self):
        """v_row/v_col/v mirror the param tree with the same reduced
        shapes as optax, so opt_state_shardings and orbax treat it alike."""
        import optax

        from learning_at_home_tpu.ops.fused_adafactor import fused_adafactor

        params = self._tree(jnp.float32)
        s_ref = optax.adafactor(1e-2).init(params)
        s_fused = fused_adafactor(1e-2).init(params)
        # optax wraps in a chain tuple; ours is the bare factored state
        ref_f = s_ref[0]
        for field in ("v_row", "v_col", "v"):
            a = jax.tree.map(jnp.shape, getattr(ref_f, field))
            b = jax.tree.map(jnp.shape, getattr(s_fused, field))
            assert a == b, (field, a, b)

    def test_weight_decay_and_no_clip_variants(self):
        import optax

        from learning_at_home_tpu.ops.fused_adafactor import fused_adafactor

        params = self._tree(jnp.float32)
        grads = self._grads(jnp.float32, seed=7)
        for kwargs in (
            {"weight_decay_rate": 1e-3},
            {"clipping_threshold": None},
            {"multiply_by_parameter_scale": False},
        ):
            ref = optax.adafactor(1e-2, **kwargs)
            fused = fused_adafactor(1e-2, **kwargs)
            u_ref, _ = ref.update(grads, ref.init(params), params)
            u_fused, _ = fused.update(grads, fused.init(params), params)
            for k in params:
                np.testing.assert_allclose(
                    np.asarray(u_fused[k]), np.asarray(u_ref[k]),
                    rtol=2e-5, atol=1e-7, err_msg=str(kwargs),
                )


def test_router_jitter_salt_decorrelates_layers():
    """The round-2 advisor finding: one fixed key gave every layer the
    identical row-to-noise map.  Folding the layer index in must produce a
    different selection-noise pattern per salt while staying deterministic."""
    from learning_at_home_tpu.ops.moe_dispatch import router_jitter

    rs = np.random.RandomState(3)
    gates = jnp.asarray(rs.rand(64, 8).astype(np.float32))
    a0 = router_jitter(gates, 0.3, salt=0)
    a0_again = router_jitter(gates, 0.3, salt=0)
    a1 = router_jitter(gates, 0.3, salt=1)
    np.testing.assert_array_equal(np.asarray(a0), np.asarray(a0_again))
    assert not np.allclose(np.asarray(a0), np.asarray(a1))
    # traced salt (the scan-over-layers case) matches the static pattern
    a1_traced = jax.jit(lambda s: router_jitter(gates, 0.3, salt=s))(
        jnp.int32(1)
    )
    np.testing.assert_allclose(np.asarray(a1_traced), np.asarray(a1), rtol=1e-6)


def test_small_top_k_matches_lax_top_k():
    from learning_at_home_tpu.ops.moe_dispatch import _small_top_k

    rs = np.random.RandomState(4)
    x = jnp.asarray(rs.randn(128, 16).astype(np.float32))
    for k in (1, 2, 4):
        w_ref, i_ref = jax.lax.top_k(x, k)
        w, i = _small_top_k(x, k)
        np.testing.assert_array_equal(np.asarray(i), np.asarray(i_ref))
        np.testing.assert_allclose(np.asarray(w), np.asarray(w_ref))
    # ties break toward the lower index, like lax.top_k
    t = jnp.asarray([[1.0, 2.0, 2.0, 0.5]])
    _, i = _small_top_k(t, 2)
    np.testing.assert_array_equal(np.asarray(i), [[1, 2]])
    with pytest.raises(ValueError):
        _small_top_k(t, 5)


class TestTokenMask:
    """Padding tokens masked out of routing (round-3 advisor: batched
    decode padding must not exhaust expert capacity ahead of real
    tokens)."""

    def test_masked_tokens_claim_no_capacity(self):
        # every token wants expert 0; capacity 2.  Unmasked, tokens 0-1
        # fill the slots and token 3 is dropped; with tokens 1-2 masked
        # as padding, token 3 (real) must get a slot instead.
        logits = jnp.asarray(
            np.tile([5.0, 0.0, 0.0, 0.0], (4, 1)), jnp.float32
        )
        mask = jnp.asarray([True, False, False, True])
        unmasked = top_k_gating(logits, k=1, capacity=2)
        assert float(unmasked.combine[3].sum()) == 0.0  # dropped
        masked = top_k_gating(logits, k=1, capacity=2, token_mask=mask)
        assert float(masked.combine[3].sum()) > 0.0  # real token fits
        # padding rows contribute nothing and occupy nothing
        assert float(masked.combine[1].sum()) == 0.0
        assert float(masked.combine[2].sum()) == 0.0
        assert not bool(masked.dispatch[1].any())
        assert not bool(masked.dispatch[2].any())
        # dropped_fraction counts only real tokens: both fit -> 0
        assert float(masked.dropped_fraction) == 0.0

    def test_indexed_plan_matches_onehot_with_mask(self):
        from learning_at_home_tpu.ops import (
            combine_outputs_indexed,
            dispatch_tokens_indexed,
            top_k_gating_indices,
        )

        rs = np.random.RandomState(3)
        logits = jnp.asarray(rs.randn(24, 6).astype(np.float32))
        mask = jnp.asarray(rs.rand(24) > 0.3)
        x = jnp.asarray(rs.randn(24, 8).astype(np.float32))
        p1 = top_k_gating(logits, k=2, capacity=4, token_mask=mask)
        p2 = top_k_gating_indices(logits, k=2, capacity=4, token_mask=mask)
        y1 = combine_outputs(dispatch_tokens(x, p1), p1)
        y2 = combine_outputs_indexed(dispatch_tokens_indexed(x, p2), p2)
        np.testing.assert_allclose(np.asarray(y1), np.asarray(y2), atol=1e-5)
        np.testing.assert_allclose(
            float(p1.dropped_fraction), float(p2.dropped_fraction), atol=1e-6
        )
        np.testing.assert_allclose(
            float(p1.aux_loss), float(p2.aux_loss), atol=1e-5
        )

    def test_expert_choice_mask_zero_weight_padding(self):
        from learning_at_home_tpu.ops.moe_dispatch import (
            expert_choice_gating,
        )

        rs = np.random.RandomState(0)
        logits = jnp.asarray(rs.randn(4, 2).astype(np.float32))
        mask = jnp.asarray([True, True, False, False])
        # capacity 3 > 2 real tokens: experts must pick real tokens first
        # and any padding picks carry zero weight
        plan = expert_choice_gating(logits, capacity=3, token_mask=mask)
        w = np.asarray(plan.weights)
        t = np.asarray(plan.token_for_slot)
        assert (w[t >= 2] == 0.0).all()  # padding tokens weightless
        # both real tokens are covered -> uncovered (over real) == 0
        assert float(plan.uncovered_fraction) == 0.0


class TestFusedCE:
    """ops/fused_ce.py: streaming-LSE CE, interpret mode (SURVEY §4 —
    kernel equivalence on CPU; on-chip validation gated on the tunnel)."""

    def _setup(self, n=256, d=128, v=2048, dtype=np.float32, seed=0):
        rs = np.random.RandomState(seed)
        x = jnp.asarray(rs.randn(n, d).astype(dtype))
        head = jnp.asarray((rs.randn(d, v) * 0.05).astype(dtype))
        t = jnp.asarray(rs.randint(0, v, n).astype(np.int32))
        return x, head, t

    def test_forward_matches_reference(self):
        import optax

        from learning_at_home_tpu.ops.fused_ce import fused_softmax_ce

        x, head, t = self._setup()
        ref = optax.softmax_cross_entropy_with_integer_labels(x @ head, t)
        ce = fused_softmax_ce(x, head, t, 128, 512, True)
        np.testing.assert_allclose(np.asarray(ce), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    def test_grads_match_reference(self):
        import optax

        from learning_at_home_tpu.ops.fused_ce import fused_softmax_ce

        x, head, t = self._setup()

        def loss_f(x, h):
            return fused_softmax_ce(x, h, t, 128, 512, True).mean()

        def loss_r(x, h):
            return optax.softmax_cross_entropy_with_integer_labels(
                x @ h, t
            ).mean()

        gx, gh = jax.grad(loss_f, argnums=(0, 1))(x, head)
        rx, rh = jax.grad(loss_r, argnums=(0, 1))(x, head)
        np.testing.assert_allclose(np.asarray(gx), np.asarray(rx), atol=1e-6)
        np.testing.assert_allclose(np.asarray(gh), np.asarray(rh), atol=1e-6)

    def test_bf16_storage_f32_stats(self):
        """bf16 operands: reductions/accumulators stay f32, so the fused
        CE must sit within bf16-rounding distance of the f32-logits
        reference computed from the SAME bf16 inputs."""
        import ml_dtypes
        import optax

        from learning_at_home_tpu.ops.fused_ce import fused_softmax_ce

        x, head, t = self._setup(dtype=ml_dtypes.bfloat16)
        ref = optax.softmax_cross_entropy_with_integer_labels(
            jnp.einsum("nd,dv->nv", x, head,
                       preferred_element_type=jnp.float32), t
        )
        ce = fused_softmax_ce(x, head, t, 128, 512, True)
        np.testing.assert_allclose(np.asarray(ce), np.asarray(ref),
                                   atol=1e-3, rtol=1e-3)

    def test_auto_falls_back_on_bad_shapes(self):
        import optax

        from learning_at_home_tpu.ops.fused_ce import fused_softmax_ce_auto

        x, head, t = self._setup(n=100, d=96, v=777)  # violates everything
        ref = optax.softmax_cross_entropy_with_integer_labels(x @ head, t)
        ce = fused_softmax_ce_auto(x, head, t, interpret=True)
        np.testing.assert_allclose(np.asarray(ce), np.asarray(ref),
                                   atol=1e-5, rtol=1e-5)

    @staticmethod
    def _counting_kernel(monkeypatch):
        """Wrap fused_softmax_ce with an invocation counter: parity
        asserts are VACUOUS if a guard silently falls back to chunked
        (both sides identical by construction), so engagement must be
        proven separately."""
        import learning_at_home_tpu.ops.fused_ce as fce

        hits = []
        orig = fce.fused_softmax_ce

        def counting(*a, **k):
            hits.append(1)
            return orig(*a, **k)

        monkeypatch.setattr(fce, "fused_softmax_ce", counting)
        return hits

    def test_loss_fn_fused_matches_chunked(self, monkeypatch):
        """ce_impl='fused' through the REAL model loss: same loss and
        same trunk gradients as the chunked path."""
        import dataclasses

        from learning_at_home_tpu.models.transformer import (
            DMoETransformerConfig,
            DMoETransformerLM,
        )
        from learning_at_home_tpu.parallel import make_mesh

        mesh = make_mesh({"expert": 1}, devices=jax.devices()[:1])
        cfg = DMoETransformerConfig(
            vocab_size=2048, d_model=128, n_layers=1, n_heads=4,
            seq_len=16, num_experts=4, k=2, dtype=jnp.float32,
            ce_chunk=64,
        )
        rs = np.random.RandomState(0)
        ids = jnp.asarray(rs.randint(0, 2048, (8, 16)), jnp.int32)
        tgt = jnp.asarray(rs.randint(0, 2048, (8, 16)), jnp.int32)

        chunked = DMoETransformerLM(cfg, mesh)
        params = chunked.init_params(jax.random.PRNGKey(0))
        fused = DMoETransformerLM(
            dataclasses.replace(cfg, ce_impl="fused"), mesh
        )

        hits = self._counting_kernel(monkeypatch)
        lc, _ = chunked.loss_fn(params, ids, tgt)
        lf, _ = fused.loss_fn(params, ids, tgt)
        assert hits, "fused-CE path fell back to chunked"
        np.testing.assert_allclose(float(lc), float(lf), rtol=1e-5)

        gc = jax.grad(lambda p: chunked.loss_fn(p, ids, tgt)[0])(params)
        gf = jax.grad(lambda p: fused.loss_fn(p, ids, tgt)[0])(params)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=2e-5
            ),
            gc, gf,
        )

    def test_loss_fn_fused_multi_device_shard_map(self, monkeypatch):
        """ce_impl='fused' on an 8-device mesh: the kernel runs per-shard
        under shard_map (replicated head, psum'd dhead cotangent) and
        must match the chunked path's loss and gradients."""
        import dataclasses

        from learning_at_home_tpu.models.transformer import (
            DMoETransformerConfig,
            DMoETransformerLM,
        )
        from learning_at_home_tpu.parallel import batch_sharding, make_mesh

        mesh = make_mesh({"data": 2, "expert": 4})
        cfg = DMoETransformerConfig(
            vocab_size=2048, d_model=128, n_layers=1, n_heads=4,
            seq_len=16, num_experts=8, k=2, dtype=jnp.float32,
            ce_chunk=64,
        )
        rs = np.random.RandomState(0)
        # batch 64: 8 rows per data-shard-group -> 8*16=128 local tokens
        ids = jax.device_put(
            jnp.asarray(rs.randint(0, 2048, (64, 16)), jnp.int32),
            batch_sharding(mesh),
        )
        tgt = jax.device_put(
            jnp.asarray(rs.randint(0, 2048, (64, 16)), jnp.int32),
            batch_sharding(mesh),
        )
        chunked = DMoETransformerLM(cfg, mesh)
        params = chunked.init_params(jax.random.PRNGKey(0))
        fused = DMoETransformerLM(
            dataclasses.replace(cfg, ce_impl="fused"), mesh
        )
        hits = self._counting_kernel(monkeypatch)
        lc, _ = jax.jit(chunked.loss_fn)(params, ids, tgt)
        lf, _ = jax.jit(fused.loss_fn)(params, ids, tgt)
        assert hits, "fused-CE shard_map path fell back to chunked"
        np.testing.assert_allclose(float(lc), float(lf), rtol=1e-5)

        gc = jax.jit(jax.grad(lambda p: chunked.loss_fn(p, ids, tgt)[0]))(params)
        gf = jax.jit(jax.grad(lambda p: fused.loss_fn(p, ids, tgt)[0]))(params)
        jax.tree.map(
            lambda a, b: np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=2e-5
            ),
            gc, gf,
        )
