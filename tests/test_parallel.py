"""Tests for the ICI tier: sharded MoE + DMoE transformer on the virtual
8-device CPU mesh (SURVEY.md §4 'TPU-build implication')."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from learning_at_home_tpu.models.transformer import (
    DMoETransformerConfig,
    DMoETransformerLM,
)
from learning_at_home_tpu.parallel import (
    ShardedMixtureOfExperts,
    batch_sharding,
    make_mesh,
)

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 virtual devices"
)


def dense_mixture(params, x):
    """Reference computation: full softmax mixture over all experts."""
    gates = jax.nn.softmax(np.asarray(x) @ params["gate"], axis=-1)
    h = np.einsum("nd,edf->enf", np.asarray(x), params["w1"]) + params["b1"][:, None]
    h = np.asarray(jax.nn.gelu(jnp.asarray(h)))
    ye = np.einsum("enf,efd->end", h, params["w2"]) + params["b2"][:, None]
    return np.einsum("ne,end->nd", gates, ye)


def test_sharded_moe_matches_dense_full_routing():
    mesh = make_mesh({"data": 2, "expert": 4})
    moe = ShardedMixtureOfExperts(
        mesh, hidden_dim=16, num_experts=8, k=8, capacity_factor=8.0,
        dtype=jnp.float32,
    )
    params = moe.init_params(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (32, 16), jnp.float32)
    y, aux = jax.jit(moe.__call__)(params, x)
    expected = dense_mixture(jax.device_get(params), x)
    np.testing.assert_allclose(np.asarray(y), expected, atol=1e-5)
    assert float(aux["dropped_fraction"]) == 0.0


def test_sharded_moe_1d_expert_mesh():
    mesh = make_mesh({"expert": 8})
    moe = ShardedMixtureOfExperts(
        mesh, hidden_dim=8, num_experts=16, k=16, capacity_factor=16.0,
        dtype=jnp.float32,
    )
    params = moe.init_params(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (16, 8), jnp.float32)
    y, _ = jax.jit(moe.__call__)(params, x)
    np.testing.assert_allclose(
        np.asarray(y), dense_mixture(jax.device_get(params), x), atol=1e-5
    )


def test_sharded_moe_grads_flow_to_experts():
    mesh = make_mesh({"data": 2, "expert": 4})
    moe = ShardedMixtureOfExperts(
        mesh, hidden_dim=16, num_experts=8, k=2, dtype=jnp.float32
    )
    params = moe.init_params(jax.random.PRNGKey(0))
    x = jax.random.normal(jax.random.PRNGKey(1), (64, 16), jnp.float32)

    def loss(params):
        y, aux = moe(params, x)
        return (y**2).mean() + 0.01 * aux["aux_loss"]

    grads = jax.jit(jax.grad(loss))(params)
    for name in ("gate", "w1", "w2"):
        assert float(jnp.abs(grads[name]).sum()) > 0, name
    # expert grads keep the expert sharding (no accidental replication)
    assert grads["w1"].sharding.spec == params["w1"].sharding.spec


def test_sharded_moe_tensor_parallel_matches_dense():
    """Experts sharded over 'expert' AND their FFN dim over 'model' (tp)."""
    mesh = make_mesh({"data": 2, "expert": 2, "model": 2})
    moe = ShardedMixtureOfExperts(
        mesh, hidden_dim=16, num_experts=4, k=4, capacity_factor=8.0,
        dtype=jnp.float32,
    )
    params = moe.init_params(jax.random.PRNGKey(0))
    assert "model" in str(params["w1"].sharding.spec)
    x = jax.random.normal(jax.random.PRNGKey(1), (32, 16), jnp.float32)
    y, aux = jax.jit(moe.__call__)(params, x)
    np.testing.assert_allclose(
        np.asarray(y), dense_mixture(jax.device_get(params), x), atol=1e-5
    )

    def loss(params):
        out, _ = moe(params, x)
        return (out**2).mean()

    grads = jax.jit(jax.grad(loss))(params)
    # tp-sharded grads keep their sharding; psum over 'model' happened
    assert grads["w1"].sharding.spec == params["w1"].sharding.spec
    assert float(jnp.abs(grads["w2"]).sum()) > 0


def test_capacity_drop_under_imbalance():
    mesh = make_mesh({"expert": 8})
    moe = ShardedMixtureOfExperts(
        mesh, hidden_dim=8, num_experts=8, k=1, capacity_factor=1.0,
        dtype=jnp.float32,
    )
    params = moe.init_params(jax.random.PRNGKey(0))
    # steer every token to expert 0 via an extreme gate
    params = dict(params)
    params["gate"] = jnp.zeros_like(params["gate"]).at[:, 0].set(100.0)
    x = jax.random.normal(jax.random.PRNGKey(1), (64, 8), jnp.float32)
    y, aux = jax.jit(moe.__call__)(params, x)
    assert float(aux["dropped_fraction"]) > 0.5  # most tokens dropped
    # dropped tokens produce zero output rows, kept ones nonzero
    row_norms = np.linalg.norm(np.asarray(y), axis=1)
    assert (row_norms == 0).sum() >= 32


def test_host_local_array_to_global():
    from jax.sharding import PartitionSpec as P

    from learning_at_home_tpu.parallel import host_local_array_to_global

    mesh = make_mesh({"data": 2, "expert": 4})
    x = np.arange(32, dtype=np.int32).reshape(16, 2)
    g = host_local_array_to_global(x, mesh)
    assert g.shape == (16, 2)
    np.testing.assert_array_equal(np.asarray(g), x)
    # default layout == batch_sharding == what the train step expects
    assert g.sharding.spec == batch_sharding(mesh).spec
    # seq-bearing mesh: sequence axis sharded too
    mesh_sp = make_mesh({"data": 2, "expert": 2, "seq": 2})
    g2 = host_local_array_to_global(np.ones((8, 4), np.float32), mesh_sp)
    assert g2.sharding.spec == batch_sharding(mesh_sp).spec
    # explicit override honored
    g3 = host_local_array_to_global(x, mesh, spec=P("data"))
    assert g3.sharding.spec == P("data")


def _tiny_model(mesh, remat=False):
    cfg = DMoETransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=4, seq_len=16,
        num_experts=8, k=2, dtype=jnp.float32, remat=remat,
    )
    return DMoETransformerLM(cfg, mesh), cfg


def test_transformer_trains_and_keeps_shardings():
    mesh = make_mesh({"data": 2, "expert": 4})
    model, cfg = _tiny_model(mesh)
    params = model.init_params(jax.random.PRNGKey(0))
    opt = optax.adamw(1e-3)
    opt_state = model.init_opt_state(opt, params)
    step = model.make_train_step(opt)

    rs = np.random.RandomState(0)
    ids = jax.device_put(
        jnp.asarray(rs.randint(0, 64, (8, 16))), batch_sharding(mesh)
    )
    tgt = jax.device_put(
        jnp.asarray(rs.randint(0, 64, (8, 16))), batch_sharding(mesh)
    )
    losses = []
    for _ in range(6):
        params, opt_state, loss, metrics = step(params, opt_state, ids, tgt)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert np.isfinite(losses).all()
    w1 = params["layers"]["moe"]["w1"]
    assert "expert" in str(w1.sharding.spec)


def test_opt_state_shardings_factored_optimizer():
    """adafactor's v_row/v_col/v reuse param key paths at REDUCED rank; they
    must be replicated, not handed the param's higher-rank spec (the exact
    crash that killed the first real-TPU bench attempt: a rank-1 ``v`` leaf
    annotated P(None, 'expert'))."""
    mesh = make_mesh({"data": 2, "expert": 4})
    model, _ = _tiny_model(mesh)
    params = model.init_params(jax.random.PRNGKey(0))
    opt = optax.adafactor(1e-3)
    opt_state = model.init_opt_state(opt, params)  # crashed before the fix
    # same-shape leaves (e.g. adamw's mu/nu) still inherit the param spec
    adam_state = model.init_opt_state(optax.adamw(1e-3), params)
    flat_p = {
        jax.tree_util.keystr(kp): v.sharding
        for kp, v in jax.tree_util.tree_flatten_with_path(params)[0]
    }
    hits = 0
    for kp, leaf in jax.tree_util.tree_flatten_with_path(adam_state)[0]:
        ks = jax.tree_util.keystr(kp)
        for pks, sharding in flat_p.items():
            if ks.endswith(pks) and "expert" in str(sharding.spec):
                assert leaf.sharding.spec == sharding.spec, (ks, leaf.sharding)
                hits += 1
    assert hits > 0
    # and the factored state actually trains
    step = model.make_train_step(opt)
    rs = np.random.RandomState(2)
    ids = jax.device_put(
        jnp.asarray(rs.randint(0, 64, (8, 16))), batch_sharding(mesh)
    )
    params, opt_state, loss, _ = step(params, opt_state, ids, ids)
    assert np.isfinite(float(loss))


def test_grad_accumulation_matches_mean_of_micro_grads():
    """accum_steps=2 must equal hand-averaged per-microbatch grads fed to
    one optimizer update (same capacity per microbatch, so exact match)."""
    mesh = make_mesh({"data": 2, "expert": 4})
    model, cfg = _tiny_model(mesh)
    params = model.init_params(jax.random.PRNGKey(0))
    opt = optax.sgd(1e-2)
    opt_state = model.init_opt_state(opt, params)
    rs = np.random.RandomState(7)
    ids = jnp.asarray(rs.randint(0, 64, (2, 8, 16)))
    tgt = jnp.asarray(rs.randint(0, 64, (2, 8, 16)))

    # reference: average grads of the two microbatches, one update
    gfn = jax.grad(lambda p, i, t: model.loss_fn(p, i, t)[0])
    g0 = gfn(params, ids[0], tgt[0])
    g1 = gfn(params, ids[1], tgt[1])
    gavg = jax.tree_util.tree_map(lambda a, b: (a + b) / 2, g0, g1)
    upd, _ = opt.update(gavg, opt_state, params)
    ref = optax.apply_updates(params, upd)

    step = model.make_train_step(opt, accum_steps=2)
    got, _, loss, metrics = step(params, opt_state, ids, tgt)
    for a, b in zip(
        jax.tree_util.tree_leaves(ref), jax.tree_util.tree_leaves(got)
    ):
        np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=2e-5, atol=2e-6,
        )
    assert np.isfinite(float(loss))
    assert 0.0 <= float(metrics["dropped_fraction"]) <= 1.0


def test_chunked_ce_matches_full_logits():
    """loss_fn's rematerialized CE must equal the full-logits loss for
    divisible AND indivisible token counts (the indivisible remainder
    goes through an extra checkpointed chunk, never full [n,V] logits)."""
    import dataclasses

    import optax as _optax

    mesh = make_mesh({"data": 2, "expert": 4})
    model, cfg = _tiny_model(mesh)
    rs = np.random.RandomState(3)
    for batch, chunk in ((8, 16), (5, 16), (3, 128)):  # n = 128, 80, 48
        m = DMoETransformerLM(
            dataclasses.replace(cfg, ce_chunk=chunk), mesh
        )
        params = m.init_params(jax.random.PRNGKey(0))
        ids = jnp.asarray(rs.randint(0, 64, (batch, 16)))
        tgt = jnp.asarray(rs.randint(0, 64, (batch, 16)))
        loss_c, _ = m.loss_fn(params, ids, tgt)
        logits, aux = m.apply(params, ids)
        ce = _optax.softmax_cross_entropy_with_integer_labels(
            logits, tgt
        ).mean()
        ref = (
            ce
            + cfg.aux_loss_weight * aux["aux_loss"]
            + cfg.router_z_weight * aux["router_z_loss"]
        )
        assert abs(float(loss_c) - float(ref)) < 1e-5, (batch, chunk)
        grads = jax.grad(lambda p: m.loss_fn(p, ids, tgt)[0])(params)
        assert all(
            bool(jnp.isfinite(l).all())
            for l in jax.tree_util.tree_leaves(grads)
        )


def test_transformer_remat_matches():
    mesh = make_mesh({"data": 2, "expert": 4})
    model, _ = _tiny_model(mesh, remat=False)
    model_r, _ = _tiny_model(mesh, remat=True)
    params = model.init_params(jax.random.PRNGKey(0))
    rs = np.random.RandomState(1)
    ids = jnp.asarray(rs.randint(0, 64, (4, 16)))
    tgt = jnp.asarray(rs.randint(0, 64, (4, 16)))
    l1, _ = jax.jit(model.loss_fn)(params, ids, tgt)
    l2, _ = jax.jit(model_r.loss_fn)(params, ids, tgt)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-5)


def test_transformer_zigzag_matches_contiguous():
    """Flagship with the model-boundary zigzag permute produces the same
    loss as the contiguous ring on identical params/batch."""
    import dataclasses

    mesh = make_mesh({"data": 2, "expert": 2, "seq": 2})
    # capacity_factor high enough that nothing drops: capacity dropping
    # is token-ORDER-dependent, and zigzag reorders tokens — with drops
    # the two layouts legitimately diverge, without them they must match
    cfg = DMoETransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=4, seq_len=16,
        num_experts=8, k=2, dtype=jnp.float32, seq_parallel=True,
        seq_layout="contiguous", capacity_factor=8.0,
    )
    model_c = DMoETransformerLM(cfg, mesh)
    model_z = DMoETransformerLM(
        dataclasses.replace(cfg, seq_layout="zigzag"), mesh
    )
    assert model_z._zig is not None  # really on the pre-permuted path
    params = model_c.init_params(jax.random.PRNGKey(0))
    rs = np.random.RandomState(2)
    ids = jnp.asarray(rs.randint(0, 64, (4, 16)))
    tgt = jnp.asarray(rs.randint(0, 64, (4, 16)))
    l1, _ = jax.jit(model_c.loss_fn)(params, ids, tgt)
    l2, _ = jax.jit(model_z.loss_fn)(params, ids, tgt)
    np.testing.assert_allclose(float(l1), float(l2), rtol=2e-5)

    # wrong sequence length must fail loudly, never silently misattend
    import pytest as _pytest

    bad = jnp.asarray(rs.randint(0, 64, (4, 8)))
    with _pytest.raises(ValueError, match="zigzag layout was built"):
        model_z.apply(params, bad)


def test_sharded_moe_expert_choice_balanced_and_trains():
    """gating='expert_choice': every expert processes exactly C tokens
    (no capacity drops, zero aux loss), output matches the dense
    reference computed from the same plan, and the transformer trains."""
    import dataclasses

    from learning_at_home_tpu.parallel.sharded_moe import (
        ShardedMixtureOfExperts,
    )

    mesh = make_mesh({"data": 2, "expert": 4})
    moe = ShardedMixtureOfExperts(
        mesh, hidden_dim=32, num_experts=8, k=2,
        dtype=jnp.float32, gating="expert_choice",
    )
    p = moe.init_params(jax.random.PRNGKey(0))
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.randn(64, 32), jnp.float32)
    y, aux = moe(p, x)
    assert y.shape == x.shape
    assert float(aux["aux_loss"]) == 0.0
    assert 0.0 <= float(aux["dropped_fraction"]) <= 1.0
    g = jax.grad(lambda p: moe(p, x)[0].sum())(p)
    w1g = g["w1"]
    # every expert got real tokens, so every expert's weights get grads
    per_expert = np.abs(np.asarray(w1g)).sum(axis=(1, 2))
    assert (per_expert > 0).all()

    model, cfg = _tiny_model(mesh)
    model = DMoETransformerLM(
        dataclasses.replace(cfg, gating="expert_choice"), mesh
    )
    params = model.init_params(jax.random.PRNGKey(0))
    opt = optax.adamw(1e-3)
    opt_state = model.init_opt_state(opt, params)
    step = model.make_train_step(opt)
    ids = jax.device_put(
        jnp.asarray(rs.randint(0, 64, (8, 16))), batch_sharding(mesh)
    )
    losses = []
    for _ in range(6):
        params, opt_state, loss, metrics = step(params, opt_state, ids, ids)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert np.isfinite(losses).all()


def test_expert_choice_rejects_router_jitter():
    from learning_at_home_tpu.parallel.sharded_moe import (
        ShardedMixtureOfExperts,
    )

    mesh = make_mesh({"expert": 8})
    with pytest.raises(ValueError, match="router_jitter"):
        ShardedMixtureOfExperts(
            mesh, hidden_dim=16, num_experts=8,
            gating="expert_choice", router_jitter=0.1,
        )


def test_attn_impl_auto_resolves_to_xla_on_cpu():
    """'auto' must never pick the TPU-only flash kernel on CPU, and an
    explicit 'xla' stays untouched."""
    import dataclasses

    mesh = make_mesh({"expert": 8})
    _, cfg = _tiny_model(mesh)
    m = DMoETransformerLM(
        dataclasses.replace(cfg, attn_impl="auto", seq_len=16), mesh
    )
    assert m.cfg.attn_impl == "xla"
    m2 = DMoETransformerLM(
        dataclasses.replace(cfg, attn_impl="xla"), mesh
    )
    assert m2.cfg.attn_impl == "xla"


def test_expert_choice_small_shard_capacity_clamps_through_moe():
    """capacity > n_local must clamp consistently through the all_to_all
    reshapes (the direct-op clamp alone left the reshape mismatched)."""
    from learning_at_home_tpu.parallel.sharded_moe import (
        ShardedMixtureOfExperts,
    )

    mesh = make_mesh({"expert": 2}, devices=jax.devices()[:2])
    # 8 tokens, E=2, k=2, factor 1.25 -> capacity 10 > n_local 8
    moe = ShardedMixtureOfExperts(
        mesh, hidden_dim=16, num_experts=2, k=2,
        dtype=jnp.float32, gating="expert_choice",
    )
    p = moe.init_params(jax.random.PRNGKey(0))
    x = jnp.asarray(np.random.RandomState(0).randn(8, 16), jnp.float32)
    y, aux = moe(p, x)
    assert y.shape == x.shape
    assert float(aux["dropped_fraction"]) == 0.0  # C=n covers all tokens


def test_generate_greedy_decode_and_shapes():
    import dataclasses

    mesh = make_mesh({"expert": 8})
    model, cfg = _tiny_model(mesh)
    params = model.init_params(jax.random.PRNGKey(0))
    prompt = jnp.asarray([[1, 2, 3], [4, 5, 6]], jnp.int32)
    out = model.generate(params, prompt, max_new_tokens=5)
    assert out.shape == (2, 8)
    np.testing.assert_array_equal(np.asarray(out[:, :3]), np.asarray(prompt))
    assert int(out.max()) < cfg.vocab_size and int(out.min()) >= 0
    # greedy decode is deterministic
    out2 = model.generate(params, prompt, max_new_tokens=5)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(out2))
    # temperature sampling needs a key, runs, and stays in range
    out3 = model.generate(
        params, prompt, max_new_tokens=5, temperature=1.0,
        rng=jax.random.PRNGKey(1),
    )
    assert out3.shape == (2, 8) and int(out3.max()) < cfg.vocab_size
    # overflow guard
    with pytest.raises(ValueError):
        model.generate(params, prompt, max_new_tokens=cfg.seq_len)


def test_expert_choice_decode_falls_back_to_token_choice(caplog):
    """VERDICT round-2 weak #5: expert-choice routing is batch-dependent;
    autoregressive decode must not silently run the model in a routing
    regime it never trained in.  decode_model() swaps in token-choice
    top-k (same gate affinities) and says so."""
    import dataclasses
    import logging

    mesh = make_mesh({"expert": 8})
    _, base = _tiny_model(mesh)
    cfg = dataclasses.replace(base, gating="expert_choice", router_jitter=0.0)
    model = DMoETransformerLM(cfg, mesh)
    params = model.init_params(jax.random.PRNGKey(0))
    with caplog.at_level(logging.WARNING):
        dm = model.decode_model()
    assert dm.cfg.gating == "topk"
    assert any("expert_choice" in r.message for r in caplog.records)
    # the fallback decodes with the TRAINED weights and stays finite
    prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
    out = model.generate(params, prompt, max_new_tokens=4)
    assert out.shape == (1, 7) and int(out.max()) < cfg.vocab_size
    # jittered token-choice models decode on clean gates
    cfg_j = dataclasses.replace(base, router_jitter=0.2)
    dm_j = DMoETransformerLM(cfg_j, mesh).decode_model()
    assert dm_j.cfg.router_jitter == 0.0


def test_padding_content_cannot_leak_into_decode_logits():
    """Round-3 advisor (medium): MoE capacity routing is cross-token, so
    with batch > 1 a row's padding tokens could exhaust expert capacity
    ahead of later rows' real tokens.  With token_mask, valid-position
    logits must be bit-independent of what the padding buffer holds."""
    # single-device mesh: the whole [B*S] buffer is ONE token shard, so
    # row 0's padding precedes row 1's real tokens in slot-claim order —
    # exactly the single-chip decode layout where the bug bites
    mesh = make_mesh({"expert": 1}, devices=jax.devices()[:1])
    cfg = DMoETransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=4, seq_len=16,
        num_experts=8, k=2, dtype=jnp.float32,
        capacity_factor=0.5,  # tight capacity: padding CAN evict real tokens
    )
    model = DMoETransformerLM(cfg, mesh)
    params = model.init_params(jax.random.PRNGKey(0))

    p = 4  # real prompt length; the rest of the buffer is padding
    rs = np.random.RandomState(0)
    prompt = rs.randint(0, 64, (2, p))
    pad_a = np.zeros((2, 16 - p), np.int64)
    pad_b = rs.randint(0, 64, (2, 16 - p))
    ids_a = jnp.asarray(np.concatenate([prompt, pad_a], axis=1))
    ids_b = jnp.asarray(np.concatenate([prompt, pad_b], axis=1))
    mask = jnp.asarray(np.arange(16)[None, :] < p).repeat(2, axis=0)

    la, _ = model.apply(params, ids_a, token_mask=mask)
    lb, _ = model.apply(params, ids_b, token_mask=mask)
    np.testing.assert_array_equal(
        np.asarray(la[:, :p]), np.asarray(lb[:, :p])
    )
    # sanity: WITHOUT the mask the tight capacity makes valid logits
    # depend on padding occupancy — the bug the mask exists to fix
    ua, _ = model.apply(params, ids_a)
    ub, _ = model.apply(params, ids_b)
    assert not np.array_equal(np.asarray(ua[:, :p]), np.asarray(ub[:, :p]))


def test_kv_cache_decode_matches_full_forward():
    """generate(use_cache=True) must reproduce the re-forward decoder's
    tokens exactly when expert capacity never binds (the one regime where
    the per-step and whole-buffer routing coincide — see generate())."""
    import dataclasses

    mesh = make_mesh({"expert": 1}, devices=jax.devices()[:1])
    cfg = DMoETransformerConfig(
        vocab_size=64, d_model=32, n_layers=2, n_heads=4, seq_len=24,
        num_experts=8, k=2, dtype=jnp.float32,
        capacity_factor=8.0,  # capacity never binds: routing identical
    )
    model = DMoETransformerLM(cfg, mesh)
    params = model.init_params(jax.random.PRNGKey(0))
    prompt = jnp.asarray([[1, 2, 3, 4], [9, 8, 7, 6]], jnp.int32)

    full = model.generate(params, prompt, max_new_tokens=8)
    cached = model.generate(params, prompt, max_new_tokens=8, use_cache=True)
    # Greedy-token comparison is only meaningful while argmax is not
    # sitting on a tie: verify the top-1/top-2 logit margin at every
    # decoded position is far above f32 noise, so a backend/dtype change
    # that perturbs low bits cannot flip a token (round-4 advisor).  A
    # genuine near-tie fails HERE, naming the position, instead of as an
    # inscrutable token mismatch below.  (capacity_factor=8 ⇒ routing on
    # the teacher-forced full sequence equals the per-step decode regime.)
    logits_all, _ = model.apply(params, jnp.asarray(full))
    p = prompt.shape[1]
    decode_logits = np.asarray(logits_all)[:, p - 1:-1]  # predicts full[:, p:]
    top2 = np.sort(decode_logits, axis=-1)[..., -2:]
    margins = top2[..., 1] - top2[..., 0]
    assert margins.min() > 1e-3, f"argmax tie at margin {margins.min()}"
    np.testing.assert_array_equal(np.asarray(full), np.asarray(cached))

    # single-token decode exercises the prefill-only path
    one = model.generate(params, prompt, max_new_tokens=1, use_cache=True)
    np.testing.assert_array_equal(np.asarray(full[:, :5]), np.asarray(one))

    # temperature sampling runs and stays in range
    t = model.generate(
        params, prompt, max_new_tokens=4, temperature=1.0,
        rng=jax.random.PRNGKey(3), use_cache=True,
    )
    assert t.shape == (2, 8) and int(t.max()) < cfg.vocab_size

    # seq_parallel is explicitly unsupported with the cache
    sp_cfg = dataclasses.replace(cfg, seq_parallel=True)
    mesh_sp = make_mesh({"expert": 4, "seq": 2})
    sp_model = DMoETransformerLM(sp_cfg, mesh_sp)
    sp_params = sp_model.init_params(jax.random.PRNGKey(0))
    with pytest.raises(NotImplementedError):
        sp_model.generate(sp_params, prompt, max_new_tokens=2, use_cache=True)


def test_kv_cache_decode_guards_row_shard_divisibility():
    """On a multi-shard mesh the cached decoder routes only B rows per
    step; B (and B*P) must divide the token shards or generate() must say
    so clearly instead of crashing inside shard_map."""
    mesh = make_mesh({"expert": 8})
    model, cfg = _tiny_model(mesh)
    params = model.init_params(jax.random.PRNGKey(0))
    prompt = jnp.asarray([[1, 2, 3], [4, 5, 6]], jnp.int32)  # B=2 < 8
    with pytest.raises(ValueError, match="token shards"):
        model.generate(params, prompt, max_new_tokens=2, use_cache=True)
    # a batch that divides the shards decodes fine (B=8, B*P=24 % 8 == 0... 
    prompt8 = jnp.asarray(np.tile([[1, 2, 3, 4]], (8, 1)), jnp.int32)
    out = model.generate(params, prompt8, max_new_tokens=2, use_cache=True)
    assert out.shape == (8, 6)


def test_generate_zero_new_tokens_returns_prompt():
    """max_new_tokens=0 is a no-op on BOTH decode paths — the cached path
    used to allocate a (b, 0) buffer and die at trace time on .at[:, 0]
    (round-4 advisor)."""
    mesh = make_mesh({"expert": 1}, devices=jax.devices()[:1])
    cfg = DMoETransformerConfig(
        vocab_size=64, d_model=32, n_layers=1, n_heads=4, seq_len=16,
        num_experts=4, k=2, dtype=jnp.float32,
    )
    model = DMoETransformerLM(cfg, mesh)
    params = model.init_params(jax.random.PRNGKey(0))
    prompt = jnp.asarray([[1, 2, 3]], jnp.int32)
    for use_cache in (False, True):
        out = model.generate(
            params, prompt, max_new_tokens=0, use_cache=use_cache
        )
        np.testing.assert_array_equal(np.asarray(out), np.asarray(prompt))
    # negative budgets are caller bugs, not no-ops
    with pytest.raises(ValueError, match="max_new_tokens"):
        model.generate(params, prompt, max_new_tokens=-1)
