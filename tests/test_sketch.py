"""Shared percentile helper + mergeable quantile sketches (ISSUE 19).

The contracts under test:

- **percentile parity**: ``percentile(..., method="linear")`` is
  bit-for-bit ``np.percentile``'s default interpolation (the loadgen and
  bench numbers must not move when they switch off numpy), and
  ``method="nearest"`` reproduces the macro-sim's historical pure-Python
  nearest-rank formula exactly, banker's rounding included;
- **sketch accuracy**: for positive values, ``quantile(q)`` is within
  ``relative_accuracy`` of the true nearest-rank percentile;
- **merge correctness**: merging sketches equals sketching the
  concatenation (bucketwise sum), and a fixture where the documented
  MAX-of-locals fallback is off by 1000× shows WHY the sketch path
  exists;
- **wire form**: JSON round-trips preserve every query; malformed wire
  dicts degrade to None (lah_top's never-crash contract), never raise;
- **registry backing**: histograms export a sketch in their snapshot,
  and ``set_sketch_backing(False)`` removes the cost.
"""

import json
import math
import random

import numpy as np
import pytest

from learning_at_home_tpu.utils.metrics import (
    MetricsRegistry,
    set_sketch_backing,
)
from learning_at_home_tpu.utils.sketch import (
    QuantileSketch,
    merge_dicts,
    percentile,
    try_from_dict,
)

QS = (0.0, 1.0, 10.0, 25.0, 50.0, 75.0, 90.0, 95.0, 99.0, 99.9, 100.0)


# ---------------------------------------------------------------------------
# percentile parity (satellite: three private helpers → one definition)
# ---------------------------------------------------------------------------


def _old_sim_pct(values, q):
    """The macro-sim's former private nearest-rank helper, verbatim."""
    if not values:
        return 0.0
    vs = sorted(values)
    idx = int(round((q / 100.0) * (len(vs) - 1)))
    return vs[min(len(vs) - 1, max(0, idx))]


def test_linear_matches_numpy_bit_for_bit():
    rng = np.random.default_rng(7)
    for n in (1, 2, 3, 4, 7, 31, 100, 999):
        vals = (rng.standard_normal(n) * 37.0 + 5.0).tolist()
        for q in QS:
            ours = percentile(vals, q, method="linear")
            theirs = float(np.percentile(np.asarray(vals), q))
            assert ours == theirs, (n, q, ours, theirs)


def test_linear_matches_numpy_on_adversarial_inputs():
    cases = [
        [1.0],
        [2.0, 1.0],
        [0.1, 0.1, 0.1],
        [1e-9, 1e9],
        [-5.0, -1.0, 0.0, 3.0],
        list(range(10)),
    ]
    for vals in cases:
        for q in QS:
            assert percentile(vals, q, method="linear") == float(
                np.percentile(np.asarray(vals, dtype=float), q)
            )


def test_nearest_matches_old_sim_formula_including_bankers_rounding():
    rng = random.Random(3)
    for n in (1, 2, 3, 5, 10, 101):
        vals = [rng.uniform(0, 100) for _ in range(n)]
        for q in QS:
            assert percentile(vals, q, method="nearest") == _old_sim_pct(
                vals, q
            ), (n, q)
    # the banker's-rounding edge the old formula had: n=2, q=50 →
    # rank 0.5 → round() → 0 → the LOWER value
    assert percentile([1.0, 9.0], 50, method="nearest") == 1.0


def test_percentile_empty_and_unknown_method():
    assert percentile([], 99) == 0.0
    assert percentile([], 99, default=-1.0) == -1.0
    with pytest.raises(ValueError):
        percentile([1.0, 2.0], 50, method="midpoint")


# ---------------------------------------------------------------------------
# sketch accuracy + merge
# ---------------------------------------------------------------------------


def test_sketch_quantile_within_relative_accuracy():
    rng = random.Random(11)
    for trial in range(4):
        vals = [rng.lognormvariate(0.0, 2.0) for _ in range(500)]
        sk = QuantileSketch()
        for v in vals:
            sk.add(v)
        for q in (1.0, 10.0, 50.0, 90.0, 95.0, 99.0, 100.0):
            truth = percentile(vals, q, method="nearest")
            est = sk.quantile(q)
            assert abs(est - truth) <= sk.relative_accuracy * truth * (
                1.0 + 1e-9
            ), (trial, q, est, truth)


def test_sketch_merge_equals_sketch_of_concatenation():
    rng = random.Random(5)
    halves = [
        [rng.expovariate(1.0) for _ in range(200)],
        [rng.expovariate(0.1) for _ in range(300)],
    ]
    whole = QuantileSketch()
    for vals in halves:
        for v in vals:
            whole.add(v)
    merged = QuantileSketch()
    for vals in halves:
        part = QuantileSketch()
        for v in vals:
            part.add(v)
        merged.merge(part)
    assert merged.count == whole.count
    assert merged.bins == whole.bins
    for q in (1.0, 50.0, 95.0, 99.0):
        assert merged.quantile(q) == whole.quantile(q)


def test_max_merge_is_provably_wrong_sketch_merge_is_right():
    """5 slow samples on one peer vs 995 fast ones on another: the true
    fleet p99 is the FAST latency (rank 989 of 1000 sits deep in the
    fast mass), but MAX-of-per-peer-p99s reports the slow peer's 1 s —
    three orders of magnitude off.  The merged sketch gets it right."""
    slow, fast = QuantileSketch(), QuantileSketch()
    for _ in range(5):
        slow.add(1.0)
    for _ in range(995):
        fast.add(0.001)
    truth = percentile([1.0] * 5 + [0.001] * 995, 99, method="nearest")
    assert truth == 0.001
    max_rule = max(slow.quantile(99), fast.quantile(99))
    assert max_rule >= 0.99  # the documented fallback: wildly pessimistic
    merged = QuantileSketch().merge(slow).merge(fast)
    assert abs(merged.quantile(99) - truth) <= 0.01 * truth * (1 + 1e-9)


def test_sketch_mismatched_accuracy_refuses_merge():
    with pytest.raises(ValueError):
        QuantileSketch(0.01).merge(QuantileSketch(0.05))


def test_sketch_zero_negative_and_nan_values():
    sk = QuantileSketch()
    for v in (0.0, -3.0, float("nan"), 2.0):
        sk.add(v)
    assert sk.count == 3  # NaN dropped, zero/negative counted
    assert sk.zero_count == 2
    assert sk.quantile(0) == -3.0  # rank 0 is the exact min
    assert sk.quantile(100) <= sk.max


def test_sketch_max_bins_collapses_lowest_first():
    sk = QuantileSketch(max_bins=16)
    for i in range(-40, 40):
        sk.add(math.exp(i))  # one value per decade-ish bucket
    assert len(sk.bins) <= 16
    # the collapse eats the LOW end: the top quantile stays accurate
    assert abs(sk.quantile(100) - math.exp(39)) <= 0.01 * math.exp(39) * (
        1 + 1e-9
    )


# ---------------------------------------------------------------------------
# wire form
# ---------------------------------------------------------------------------


def test_wire_form_json_round_trip_preserves_queries():
    rng = random.Random(9)
    sk = QuantileSketch()
    for _ in range(300):
        sk.add(rng.lognormvariate(0.0, 1.5))
    back = QuantileSketch.from_dict(json.loads(json.dumps(sk.to_dict())))
    assert back.count == sk.count and back.sum == sk.sum
    assert back.min == sk.min and back.max == sk.max
    for q in (1.0, 50.0, 99.0):
        assert back.quantile(q) == sk.quantile(q)


def test_wire_form_empty_sketch_round_trip():
    back = QuantileSketch.from_dict(QuantileSketch().to_dict())
    assert back.count == 0 and back.quantile(99) == 0.0


def test_try_from_dict_tolerates_garbage():
    good = QuantileSketch()
    good.add(1.0)
    wire = good.to_dict()
    assert try_from_dict(wire) is not None
    for junk in (
        None, 7, "sketch", [], {},
        {"kind": "histogram"},  # wrong discriminator
        {**wire, "ra": "fast"},  # non-numeric accuracy
        {**wire, "bins": [[0]]},  # malformed pair
        {**wire, "bins": [[0, -5]]},  # negative bucket count
        {**wire, "count": None},
    ):
        assert try_from_dict(junk) is None, junk


def test_merge_dicts_skips_malformed_and_none_when_nothing_merged():
    a, b = QuantileSketch(), QuantileSketch()
    for _ in range(10):
        a.add(0.5)
        b.add(2.0)
    merged = merge_dicts([a.to_dict(), {"kind": "nope"}, b.to_dict()])
    assert merged is not None and merged.count == 20
    assert merge_dicts([]) is None
    assert merge_dicts([None, {}, "x"]) is None


# ---------------------------------------------------------------------------
# registry histograms carry sketches (the /metrics.json wire path)
# ---------------------------------------------------------------------------


def test_registry_histogram_exports_sketch_and_backing_toggle():
    reg = MetricsRegistry()
    try:
        h = reg.histogram("lah_t_lat_seconds")
        for v in (0.001, 0.002, 0.004, 1.0):
            h.observe(v)
        snap = reg.snapshot()
        wire = snap["histograms"]["lah_t_lat_seconds"]["sketch"]
        json.dumps(wire)  # JSON-safe in place
        sk = try_from_dict(wire)
        assert sk is not None and sk.count == 4
        # labeled histograms carry one sketch per label variant
        hl = reg.histogram("lah_t_lbl_seconds")
        hl.observe(0.5, pool="a")
        hl.observe(2.5, pool="b")
        labelled = reg.snapshot()["histograms"]["lah_t_lbl_seconds"]
        variants = [v for v in labelled.values() if isinstance(v, dict)]
        assert len(variants) == 2
        assert all(try_from_dict(v["sketch"]) is not None for v in variants)
        # backing off: fresh observations stop growing a sketch
        set_sketch_backing(False)
        reg2 = MetricsRegistry()
        h2 = reg2.histogram("lah_t_plain_seconds")
        h2.observe(0.5)
        assert "sketch" not in reg2.snapshot()["histograms"][
            "lah_t_plain_seconds"
        ]
    finally:
        set_sketch_backing(True)
