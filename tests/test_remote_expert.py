"""M1 integration tests: real Server on localhost + RemoteExpert stubs.

Mirrors the reference's test_moe.py-style integration tier (SURVEY.md §4):
remote forward/backward must match an identical local module numerically,
including gradient flow through the custom-vjp network boundary."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from learning_at_home_tpu.client import RemoteExpert, reset_client_rpc
from learning_at_home_tpu.models import make_expert
from learning_at_home_tpu.server import ExpertBackend
from learning_at_home_tpu.server.server import Server, background_server

HID = 32


@pytest.fixture(scope="module")
def server():
    with background_server(num_experts=2, hidden_dim=HID, seed=42) as (endpoint, srv):
        yield endpoint, srv
    reset_client_rpc()


def local_twin(seed, uid_index):
    rng = jax.random.PRNGKey(seed + uid_index)
    return make_expert("ffn", HID, rng, jnp.zeros((2, HID)))


def test_remote_forward_matches_local(server):
    endpoint, _ = server
    expert = RemoteExpert("expert.0", endpoint)
    apply_fn, params = local_twin(42, 0)
    x = np.random.RandomState(0).randn(8, HID).astype(np.float32)
    out = expert(x)
    expected = apply_fn(params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=1e-5)


def test_remote_forward_under_jit(server):
    endpoint, _ = server
    expert = RemoteExpert("expert.1", endpoint)
    apply_fn, params = local_twin(42, 1)
    x = np.random.RandomState(1).randn(4, HID).astype(np.float32)

    @jax.jit
    def step(x):
        return expert(x) * 2.0

    out = step(x)
    expected = apply_fn(params, x) * 2.0
    np.testing.assert_allclose(np.asarray(out), np.asarray(expected), atol=1e-5)


def test_remote_grad_matches_local_and_updates_server(server):
    endpoint, srv = server
    expert = RemoteExpert("expert.0", endpoint)
    apply_fn, params = local_twin(42, 0)
    x = np.random.RandomState(2).randn(4, HID).astype(np.float32)

    # server params may already have been updated by other tests in this
    # module — read the live state for the expectation instead
    live_params = srv.experts["expert.0"].state_dict()["params"]

    def local_loss(x):
        return jnp.sum(apply_fn(live_params, x) ** 2)

    def remote_loss(x):
        return jnp.sum(expert(x) ** 2)

    expected_grad = jax.grad(local_loss)(jnp.asarray(x))
    before = srv.experts["expert.0"].update_count
    got_grad = jax.grad(remote_loss)(jnp.asarray(x))
    np.testing.assert_allclose(
        np.asarray(got_grad), np.asarray(expected_grad), atol=1e-3, rtol=1e-3
    )
    # the backward RPC must have applied the server-side async optimizer step
    assert srv.experts["expert.0"].update_count == before + 1


def test_info_rpc(server):
    endpoint, _ = server
    info = RemoteExpert("expert.1", endpoint).info()
    assert info["name"] == "expert.1"
    assert info["num_params"] > 0


def test_unknown_expert_raises(server):
    endpoint, _ = server
    from learning_at_home_tpu.utils.connection import RemoteCallError

    with pytest.raises(RemoteCallError, match="unknown expert"):
        RemoteExpert("nonexistent.99", endpoint).forward_blocking(
            [np.zeros((1, HID), np.float32)]
        )


def test_cross_client_batching(server):
    """Many concurrent remote calls get batched into few device batches."""
    endpoint, srv = server
    expert = RemoteExpert("expert.1", endpoint)
    pool = srv.forward_pools["expert.1"]
    formed_before = pool.batches_formed

    import concurrent.futures as cf

    xs = [np.random.randn(2, HID).astype(np.float32) for _ in range(16)]
    with cf.ThreadPoolExecutor(16) as ex:
        outs = list(ex.map(lambda x: expert.forward_blocking([x])[0], xs))
    apply_fn, params = local_twin(42, 1)
    live = srv.experts["expert.1"].state_dict()["params"]
    for x, out in zip(xs, outs):
        np.testing.assert_allclose(
            out, np.asarray(apply_fn(live, x)), atol=1e-4, rtol=1e-4
        )
    formed = srv.forward_pools["expert.1"].batches_formed - formed_before
    assert formed < 16  # if batching broke, every request would form its own batch


def test_server_create_classmethod():
    """Server.create: zoo-built experts, optional warmup, full serve cycle."""
    from learning_at_home_tpu.server import Server

    srv = Server.create(
        num_experts=2, hidden_dim=16, expert_prefix="zoo", host="127.0.0.1",
        warmup=False,
    )
    try:
        e = RemoteExpert("zoo.0", srv.endpoint)
        x = np.random.RandomState(0).randn(2, 16).astype(np.float32)
        (out,) = e.forward_blocking([x])
        assert out.shape == (2, 16)
        assert e.info()["name"] == "zoo.0"
    finally:
        srv.shutdown()


def test_server_stats_op():
    """The server-wide `stats` op: one round trip returns update counts
    and pool/padding counters for every hosted expert (docs/PROTOCOL.md)."""
    import numpy as np

    from learning_at_home_tpu.client.rpc import client_loop, pool_registry
    from learning_at_home_tpu.server.server import background_server

    with background_server(
        num_experts=2, hidden_dim=8, expert_prefix="st", seed=0
    ) as (endpoint, srv):
        from learning_at_home_tpu.client import RemoteExpert

        e = RemoteExpert("st.0", endpoint)
        x = np.random.RandomState(0).randn(3, 8).astype(np.float32)
        e.forward_blocking([x])
        e.backward_blocking([x], [x])  # applies one async update

        async def stats():
            _, meta = await pool_registry().get(endpoint).rpc(
                "stats", (), {}, timeout=10.0
            )
            return meta

        s = client_loop().run(stats())
    assert s["n_experts"] == 2
    assert s["update_count_total"] == 1
    assert s["update_count"]["st.0"] == 1 and s["update_count"]["st.1"] == 0
    assert s["pools"]["forward"]["batches_formed"] >= 1
    assert s["pools"]["forward"]["rows"] >= 3
    assert 0.0 <= s["pools"]["forward"]["padding_waste"] < 1.0
